"""HVD001 fixture pair for the per-bucket collective emission pattern
(PR 6 jit-overlap / shared bucketing layer): looping over a
deterministic bucket partition and submitting one collective per
bucket is UNIFORM — every process derives the identical bucket list
from the identical gradient tree (the partition is a pure function of
structure/shapes/threshold, pinned by tests/test_bucketing.py), so the
schedule cannot diverge and none of it may be reported. The positive
twin shows the SAME loop shape made divergent by a rank-dependent
bucket selection, which must still be caught.
"""

import horovod_tpu as hvd
from horovod_tpu.ops.bucketing import partition_buckets


def per_bucket_emission(leaves):
    # negative: bucket list is rank-independent; one grouped
    # submission per bucket is the uniform schedule the eager
    # DistributedOptimizer and the jit overlap path both emit.
    out = list(leaves)
    for bucket in partition_buckets(leaves, 64 * 1024 * 1024):
        reduced = hvd.grouped_allreduce(
            [leaves[i] for i in bucket.indices])
        for i, r in zip(bucket.indices, reduced):
            out[i] = r
    return out


def per_bucket_emission_with_flag(leaves, flag):
    # negative: the numerics finite-flag riding the trailing bucket is
    # still an unconditional, uniform submission.
    buckets = partition_buckets(leaves + [flag], 1 << 20)
    outs = []
    for bucket in buckets:
        outs.append(hvd.grouped_allreduce(
            [(leaves + [flag])[i] for i in bucket.indices]))
    return outs


def rank_selected_bucket_is_still_divergent(leaves):
    # positive: slicing the bucket list by rank() makes each process
    # submit a DIFFERENT schedule — the classic deadlock, loop shape
    # or not.
    buckets = partition_buckets(leaves, 1 << 20)
    mine = buckets[hvd.rank() % len(buckets)]
    if hvd.rank() == 0:
        return hvd.grouped_allreduce(  # EXPECT: HVD001
            [leaves[i] for i in mine.indices])
    return leaves


def rank_gated_bucket_loop(leaves):
    # positive: an early rank guard taints everything after it,
    # including the per-bucket loop body.
    if hvd.rank() != 0:
        return leaves
    for bucket in partition_buckets(leaves, 1 << 20):
        hvd.grouped_allreduce(  # EXPECT: HVD001
            [leaves[i] for i in bucket.indices])
    return leaves
