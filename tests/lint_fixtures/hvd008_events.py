"""HVD008 fixture: seeded event-schema positives/negatives.

Declares its own miniature EVENT_SCHEMAS registry — the analyzer
adopts the first declaring file in the scanned set, so the corpus is
self-contained and never reads the real journal.py (and, because this
file is not named journal.py, the docs-drift leg stays off). The
legacy hvd004_* fixtures write four real event names
(commit / seq_watermark / batch_admitted / weights_adopted) with
partial fields; the
registry declares relaxed shims for those so the HVD004 corpus stays
HVD008-clean.
"""

import dataclasses
from typing import List, Tuple


@dataclasses.dataclass(frozen=True)
class EventSchema:
    name: str
    writer: str
    doc: str
    required: Tuple[str, ...] = ()
    optional: Tuple[str, ...] = ()
    critical: bool = False


BASE_FIELDS = frozenset({"type", "role", "rank", "pid", "mono_ns",
                         "t", "n"})

EVENT_SCHEMAS: List[EventSchema] = [
    EventSchema("fx_commit", "worker", "Fixture commit edge.",
                required=("epoch",), optional=("durable",),
                critical=True),
    EventSchema("fx_probe", "serving", "Fixture probe record.",
                required=("batch", "cause")),
    EventSchema("fx_dead", "driver", "Never written anywhere."),  # EXPECT: HVD008
    # Relaxed shims for the legacy hvd004_* fixtures' write sites —
    # those files exercise trace purity, not schemas.
    EventSchema("commit", "worker", "Legacy shim.",
                optional=("step",)),
    EventSchema("seq_watermark", "serving", "Legacy shim.",
                optional=("sid", "token")),
    EventSchema("batch_admitted", "serving", "Legacy shim.",
                optional=("batch",)),
    EventSchema("weights_adopted", "worker", "Legacy shim.",
                optional=("digest",)),
]


class _Journal:
    def record(self, type_, **fields):
        return type_, fields


journal = _Journal()


# -- writer side -----------------------------------------------------------


def conformant_write():
    journal.record("fx_commit", epoch=3, durable=True)


def undeclared_event():
    journal.record("fx_ghost", epoch=1)  # EXPECT: HVD008


def missing_required_field():
    journal.record("fx_probe", batch=7)  # EXPECT: HVD008


def undeclared_field():
    journal.record("fx_probe", batch=7, cause="x", causee="y")  # EXPECT: HVD008


def star_kwargs_suppress_missing_check(fields):
    # the analyzer cannot see through **expansion: required-field
    # enforcement is the runtime strict mode's job here
    journal.record("fx_probe", **fields)


def dynamic_name_is_unverifiable(name):
    journal.record(name, batch=1)


def underscore_kwargs_are_plumbing():
    journal.record("fx_commit", epoch=1, _critical=True)


def suppressed_write():
    # hvdlint: disable-next=HVD008 (fixture: exercising suppression)
    journal.record("fx_ghost2", x=1)


def non_journal_receivers_do_not_match(tuner):
    # a .record() on a non-journal receiver is a different seam
    tuner.record("fx_ghost3", sample=1)


# -- consumer side ---------------------------------------------------------


def consumer_guard_and_fields_ok(events):
    for e in events:
        if e["type"] == "fx_commit":
            yield e["epoch"], e.get("durable"), e["rank"], e.get("_src")


def consumer_stale_type_key(events):
    return [e for e in events if e["type"] == "fx_removed"]  # EXPECT: HVD008


def consumer_alias_misspelled_field(events):
    for e in events:
        ty = e["type"]
        if ty == "fx_probe":
            yield e["batch"], e.get("caus")  # EXPECT: HVD008


def consumer_membership_with_zombie(events):
    keep = ("fx_commit", "fx_zombie")
    return [e for e in events if e["type"] in keep]  # EXPECT: HVD008


def consumer_comp_filter_misspelled_field(events):
    probes = [e for e in events if e["type"] == "fx_probe"]
    return [(p["batch"], p["causey"]) for p in probes]  # EXPECT: HVD008


def consumer_next_probe_misspelled_field(events):
    meta = next((e for e in events if e["type"] == "fx_commit"), {})
    return meta.get("epoch"), meta.get("epochh")  # EXPECT: HVD008


def consumer_unconstrained_reads_are_fine(events):
    # no narrowing: a generic walk may read anything
    return [e.get("whatever") for e in events]


def consumer_else_branch_is_unconstrained(events):
    for e in events:
        if e["type"] == "fx_commit":
            yield e["epoch"]
        else:
            yield e.get("anything_at_all")
