"""HVD003 fixture: blocking-under-lock and lock-order inversions."""

import subprocess
import threading
import time

_lock = threading.Lock()
_other_mu = threading.Lock()


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()

    def sleep_under_lock(self):
        with self._lock:
            time.sleep(1.0)  # EXPECT: HVD003

    def socket_io_under_lock(self, sock, payload):
        with self._lock:
            sock.sendall(payload)  # EXPECT: HVD003
            return sock.recv(4)  # EXPECT: HVD003

    def subprocess_under_lock(self, cmd):
        with self._lock:
            return subprocess.check_output(cmd)  # EXPECT: HVD003

    def event_wait_under_lock(self):
        with self._lock:
            self._stop.wait(1.0)  # EXPECT: HVD003

    def condition_wait_is_fine(self):
        # Condition.wait on the held lock RELEASES it: not blocking.
        with self._cv:
            self._cv.wait(1.0)

    def deferred_body_is_fine(self):
        with self._lock:
            def later():
                time.sleep(5.0)
            return later

    def sleep_outside_lock_is_fine(self):
        with self._lock:
            n = 3
        time.sleep(0.1)
        return n

    def suppressed(self):
        with self._lock:
            # hvdlint: disable-next=HVD003 (fixture: serialization of
            # this io is the lock's entire purpose)
            time.sleep(0.5)


def order_ab():
    with _lock:
        with _other_mu:  # EXPECT: HVD003
            pass


def order_ba():
    with _other_mu:
        with _lock:
            pass
