"""HVD002 fixture registry: a miniature common/config.py clone so the
registry-enforcement pass has declared knobs to check against."""

from typing import Any, Callable, List


class Knob:
    def __init__(self, env: str, type: Callable[[str], Any],
                 default: Any, doc: str):
        self.env = env
        self.type = type
        self.default = default
        self.doc = doc


KNOBS: List[Knob] = [
    Knob("HOROVOD_FIXTURE_USED", int, 1, "Declared and used."),
    Knob("HOROVOD_FIXTURE_DECLARED", str, "", "Declared; read "
         "directly via os.environ elsewhere (a bypass)."),
    Knob("HOROVOD_FIXTURE_UNUSED", int, 0,  # EXPECT: HVD002
         "Declared but never used anywhere: dead config surface."),
]


class Config:
    _ATTR_MAP = {
        "fixture_used": "HOROVOD_FIXTURE_USED",
    }
