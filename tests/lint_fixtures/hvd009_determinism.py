"""HVD009 fixture: seeded byte-determinism positives/negatives.

Declares DETERMINISTIC_ENTRYPOINTS so the rule seeds its reachability
here; every positive sits in a helper an entry point actually calls,
and the file also proves the frontier is honest — the same wall-clock
read OUTSIDE the reach stays unflagged (that is HVD004's beat for
traced functions, not this rule's).
"""

import glob
import json
import os
import random
import time

DETERMINISTIC_ENTRYPOINTS = ("render_fixture_report",
                             "digest_fixture_dir")


# -- entry point 1: report rendering ---------------------------------------


def render_fixture_report(rows):
    doc = {"rows": _normalized(rows), "jitter": _jitter(),
           "stamp": _stamped()}
    return json.dumps(doc, indent=1, sort_keys=True)


def _stamped():
    return time.time()  # EXPECT: HVD009


def _jitter():
    return random.random()  # EXPECT: HVD009


def _normalized(rows):
    out = []
    for r in set(rows):  # EXPECT: HVD009
        out.append(r)
    for r in sorted(set(rows)):  # sorted wrapper: deterministic
        out.append(r)
    return out


# -- entry point 2: directory digest ---------------------------------------


def digest_fixture_dir(dir_):
    names = []
    for n in os.listdir(dir_):  # EXPECT: HVD009
        names.append(n)
    segs = glob.glob(os.path.join(dir_, "*.jsonl"))
    for s in segs:  # EXPECT: HVD009
        names.append(s)
    ordered = sorted(glob.glob(os.path.join(dir_, "*.json")))
    for s in ordered:  # assign-through-sorted: deterministic
        names.append(s)
    resorted = glob.glob(os.path.join(dir_, "*.txt"))
    resorted.sort()
    for s in resorted:  # .sort() before iterating: deterministic
        names.append(s)
    names.append(_latest(dir_))
    names.append(_keyed(names))
    names.append(_seeded_is_fine())
    names.append(suppressed_reachable_read())
    return json.dumps({"names": names})  # EXPECT: HVD009


def _latest(dir_):
    # order-insensitive reduction over a glob: deterministic
    pbs = glob.glob(os.path.join(dir_, "*.pb"))
    return max(pbs) if pbs else None


def _keyed(obj):
    return id(obj)  # EXPECT: HVD009


def _seeded_is_fine():
    rng = random.Random(17)
    return rng.random()


# -- outside the reach: none of this may be reported -----------------------


def unreachable_wallclock_is_not_our_beat():
    # not reachable from any DETERMINISTIC_ENTRYPOINTS seed: runtime
    # nondeterminism belongs to the runtime rules (HVD004 for traced
    # fns), not the artifact plane
    return time.time(), random.random(), json.dumps({"a": 1})


def suppressed_reachable_read():
    # reachable from digest_fixture_dir, so the suppression is
    # exercised rather than dead code
    # hvdlint: disable-next=HVD009 (fixture: exercising suppression)
    return time.monotonic_ns()
