"""HVD006 fixture: lockset races on fields written from >=2 thread
entry points — seeded positives (EXPECT-anchored) and negatives."""

import signal
import threading


class DisjointLocks:
    """The classic Eraser shape: both writers lock, but not the SAME
    lock, so the locks protect nothing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._pace,
                                        daemon=True)

    def start(self):
        self._thread.start()

    def _pace(self):
        while True:
            with self._io_lock:
                self.count += 1  # EXPECT: HVD006

    def bump(self):
        with self._lock:
            self.count += 1


class UnlockedCounter:
    """No lock at all on a field the drain thread and callers share."""

    def __init__(self):
        self.nbytes = 0
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        while True:
            self.nbytes += 10  # EXPECT: HVD006

    def add(self, n):
        self.nbytes += n


_signal_flips = 0


def _on_usr1(signum, frame):
    global _signal_flips
    _signal_flips += 1  # EXPECT: HVD006


def install_handler():
    signal.signal(signal.SIGUSR1, _on_usr1)


def record_flip():
    global _signal_flips
    _signal_flips += 1


# -- negatives: none of these may be reported -------------------------------

class OneLockEverywhere:
    def __init__(self):
        self._lock = threading.Lock()
        self.safe = 0
        threading.Thread(target=self._pace, daemon=True).start()

    def _pace(self):
        while True:
            with self._lock:
                self.safe += 1

    def bump(self):
        with self._lock:
            self.safe += 1


class LockHeldAtEveryCallSite:
    """Interprocedural: the helper writes with no lexical lock, but
    every resolved call site holds the same one."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0
        threading.Thread(target=self._pace, daemon=True).start()

    def _pace(self):
        while True:
            with self._lock:
                self._bump_locked()

    def public(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self.value += 1


class InitOnlyThenThread:
    """__init__ publication happens-before Thread.start(): the loop
    is then the only writer."""

    def __init__(self):
        self.state = "ready"
        self.ticks = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self.ticks += 1


class MainOnly:
    def __init__(self):
        self.total = 0

    def add(self, n):
        self.total += n

    def reset(self):
        self.total = 0


class SuppressedPublish:
    def __init__(self):
        self.flag = False
        threading.Thread(target=self._watch, daemon=True).start()

    def _watch(self):
        while True:
            # hvdlint: disable-next=HVD006 (fixture: GIL-atomic bool
            # publish, single store, benign by design)
            self.flag = True

    def arm(self):
        self.flag = False
