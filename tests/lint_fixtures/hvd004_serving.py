"""HVD004 fixture: serving-worker side-effects inside the traced
forward (round 15).

serving.py's contract is that the seam fire, the metrics, and the
journal records all live in the UNTRACED worker loop around the
AOT-compiled forward. These positives are the tempting wrong version
— instrumenting the forward itself — which would bake one trace-time
sample into the executable; the negatives are the loop shape the
subsystem actually uses.
"""

import time

import jax
import jax.numpy as jnp

from horovod_tpu import faults
from horovod_tpu.metrics import REGISTRY

_m_fix_batches = REGISTRY.counter(
    "hvdfix_serving_batches_total",
    "Seeded serving trace-impurity target.")


@jax.jit
def forward_counts_batches(x):
    _m_fix_batches.inc()  # EXPECT: HVD004
    return jnp.tanh(x)


@jax.jit
def forward_fires_seam(x):
    faults.fire("serving.batch")  # EXPECT: HVD004
    return x * 2


@jax.jit
def forward_times_itself(x):
    t0 = time.perf_counter()  # EXPECT: HVD004
    return x * t0


@jax.jit
def forward_journals_admission(x):
    from horovod_tpu import journal
    journal.record("batch_admitted", batch="b1")  # EXPECT: HVD004
    return x + 1


# -- negatives: the worker-loop shape serving.py actually uses -------------

@jax.jit
def pure_forward(x):
    return jnp.tanh(x)


def worker_loop_effects_outside_trace(x):
    # seam fire, metric, latency clock and journal record wrap the
    # compiled forward from plain python — the intended split
    faults.fire("serving.batch")
    _m_fix_batches.inc()
    t0 = time.perf_counter()
    y = pure_forward(x)
    from horovod_tpu import journal
    journal.record("batch_admitted", batch="b2")
    return y, time.perf_counter() - t0
