"""HVD002 fixture: seeded registry-enforcement positives/negatives."""

import os

from horovod_tpu.metrics import REGISTRY


def undeclared_read():
    return os.environ.get("HOROVOD_FIXTURE_MYSTERY", "")  # EXPECT: HVD002


def declared_but_bypassing_read():
    return os.getenv("HOROVOD_FIXTURE_DECLARED")  # EXPECT: HVD002


def subscript_read():
    return os.environ["HOROVOD_FIXTURE_DECLARED"]  # EXPECT: HVD002


def suppressed_read():
    # hvdlint: disable-next=HVD002 (fixture: launch plumbing)
    return os.environ.get("HOROVOD_FIXTURE_DECLARED", "")


def uses_the_registry(cfg):
    # attribute access through _ATTR_MAP counts as a use
    return cfg.fixture_used


def writes_are_plumbing_not_reads():
    # child-env propagation: none of these may be reported
    os.environ["HOROVOD_FIXTURE_DECLARED"] = "x"
    os.environ.pop("HOROVOD_FIXTURE_DECLARED", None)
    os.environ.setdefault("HOROVOD_FIXTURE_DECLARED", "y")


def non_horovod_reads_are_fine():
    return os.environ.get("PATH", "")


_m_ok = REGISTRY.counter(
    "hvdfix_single_registration_total", "Registered exactly once: ok.")

_m_dup_a = REGISTRY.counter(
    "hvdfix_duplicated_total", "First site wins.")
_m_dup_b = REGISTRY.counter(  # EXPECT: HVD002
    "hvdfix_duplicated_total", "Second site: registry drift hazard.")


def lookup_of_never_registered_name():
    return REGISTRY.get("hvdfix_typo_total")  # EXPECT: HVD002


def lookup_of_registered_name_is_fine():
    return REGISTRY.get("hvdfix_single_registration_total")


# -- recovery SLO metrics (round 11: journal.py's hvd_recovery_*) ----------

_m_recovery_ok = REGISTRY.histogram(
    "hvdfix_recovery_seconds",
    "Registered exactly once: ok.", ("phase",))

_m_recovery_dup = REGISTRY.histogram(  # EXPECT: HVD002
    "hvdfix_recovery_seconds",
    "Second registration site: the drift hazard HVD002 guards the "
    "real hvd_recovery_seconds against.", ("phase",))


def lookup_of_never_registered_recovery_metric():
    return REGISTRY.get("hvdfix_recovery_oops_total")  # EXPECT: HVD002


def lookup_of_registered_recovery_metric_is_fine():
    return REGISTRY.get("hvdfix_recovery_seconds")
