"""Historical-bug regression corpus: the three defects this repo
actually shipped and later fixed, reconstructed in miniature, each
asserting the analyzer would now catch it at lint time.

  * PR 1 — the unlocked `_bytes_processed` accumulation raced between
    the caller thread and the controller's dispatch worker (HVD006).
  * PR 4 — `subprocess.Popen` spawned while holding `TaskService._lock`
    serialized every contender behind process startup (HVD003).
  * PR 6 — torch async handles submitted but never synchronized leaked
    their engine entries for the life of the session (HVD005).
"""

import subprocess
import threading

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops


class Pr1BytesProcessedRace:
    """PR 1: `self._bytes_processed += nbytes` from both the inline
    caller path and the controller's background dispatch worker, no
    lock — the fix made it a thread-safe Counter."""

    def __init__(self):
        self._bytes_processed = 0
        self._worker = threading.Thread(target=self._dispatch_loop,
                                        daemon=True)

    def _dispatch_loop(self):
        while True:
            self._bytes_processed += 1024  # EXPECT: HVD006

    def run_inline(self, nbytes):
        self._bytes_processed += nbytes


class Pr4PopenUnderLock:
    """PR 4: claim-then-spawn was the fix; the bug held the service
    lock across the process spawn."""

    def __init__(self):
        self._lock = threading.Lock()
        self._procs = []

    def spawn(self, cmd):
        with self._lock:
            proc = subprocess.Popen(cmd)  # EXPECT: HVD003
            self._procs.append(proc)
        return proc


class Pr6HandleLeak:
    """PR 6: handles submitted on the skip_synchronize path were never
    drained, so their engine entries (and torch meta) lived forever."""

    def __init__(self):
        self._should_sync = True

    def step(self, grads):
        h = hvd.grouped_allreduce_async(grads)  # EXPECT: HVD005
        if self._should_sync:
            return collective_ops.synchronize(h)
        return grads
