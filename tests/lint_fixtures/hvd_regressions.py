"""Historical-bug regression corpus: the defects this repo actually
shipped and later fixed, reconstructed in miniature, each asserting
the analyzer would now catch it at lint time.

AST tier (run_analysis; EXPECT-anchored):
  * PR 1 — the unlocked `_bytes_processed` accumulation raced between
    the caller thread and the controller's dispatch worker (HVD006).
  * PR 4 — `subprocess.Popen` spawned while holding `TaskService._lock`
    serialized every contender behind process startup (HVD003).
  * PR 6 — torch async handles submitted but never synchronized leaked
    their engine entries for the life of the session (HVD005).
  * PR 18 schema drift — the decode doctor keyed resume watermarks on
    a misspelled field, silently dropping every record it was written
    to count (HVD008).
  * PR 18 byte-identity flake — the trajectory consolidation walked
    per-round bench artifacts with an unsorted glob, so regenerated
    reports matched the committed bytes only when the filesystem
    happened to agree (HVD009).

Jaxpr tier (HVD007, traced by TestHistoricalRegressions through
analysis.jaxpr_verify.verify_traced — no EXPECT markers because these
are IR-level defects the AST pass cannot see, which is the point):
  * PR 8 bug #1 — the monolithic reduction leg emitted psums over
    size-1 mesh axes (identity wire: the full pack/reduce round trip
    with zero bytes to move, shipped in every world-1 step).
  * PR 8 bug #2 — the legacy-jax psum transpose re-reduced an
    already-reduced gradient over the same axis, so gradients arrived
    exactly |axis|x too large.
  * PR 13 — the first compression draft let the finite-flag ride the
    fp16-cast wire carrier (one fused n+1 psum in half precision).
    A veto count accumulated in a lossy dtype rounds n-1 up to n past
    a few hundred ranks, silently disabling the numerics guard at
    exactly the scale it exists for; HVD007's check (e) must flag the
    planned ride and the missing separate exact f32 vote.
"""

import glob
import json
import subprocess
import threading

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops

DETERMINISTIC_ENTRYPOINTS = ("pr18_trajectory_consolidate",)


class Pr1BytesProcessedRace:
    """PR 1: `self._bytes_processed += nbytes` from both the inline
    caller path and the controller's background dispatch worker, no
    lock — the fix made it a thread-safe Counter."""

    def __init__(self):
        self._bytes_processed = 0
        self._worker = threading.Thread(target=self._dispatch_loop,
                                        daemon=True)

    def _dispatch_loop(self):
        while True:
            self._bytes_processed += 1024  # EXPECT: HVD006

    def run_inline(self, nbytes):
        self._bytes_processed += nbytes


class Pr4PopenUnderLock:
    """PR 4: claim-then-spawn was the fix; the bug held the service
    lock across the process spawn."""

    def __init__(self):
        self._lock = threading.Lock()
        self._procs = []

    def spawn(self, cmd):
        with self._lock:
            proc = subprocess.Popen(cmd)  # EXPECT: HVD003
            self._procs.append(proc)
        return proc


class Pr6HandleLeak:
    """PR 6: handles submitted on the skip_synchronize path were never
    drained, so their engine entries (and torch meta) lived forever."""

    def __init__(self):
        self._should_sync = True

    def step(self, grads):
        h = hvd.grouped_allreduce_async(grads)  # EXPECT: HVD005
        if self._should_sync:
            return collective_ops.synchronize(h)
        return grads


def pr18_watermark_field_drift(events):
    """PR 18 schema drift: the decode doctor's watermark census read
    `w.get("tokn")` — a misspelling of the declared `token` field —
    which returned None for every record, so the resume-watermark
    count silently collapsed to zero and the doctor reported a clean
    decode tier while sequences were being replayed from scratch.
    HVD008's consumer leg must flag the read against the registry."""
    high = {}
    for w in events:
        if w["type"] == "seq_watermark":
            high[w["sid"]] = w.get("tokn")  # EXPECT: HVD008
    return high


def pr18_trajectory_consolidate(dir_):
    """PR 18 byte-identity flake: `bench --trajectory` consolidation
    walked the per-round artifacts with an unsorted glob, so the row
    order of the regenerated BENCH_trajectory.json depended on
    filesystem enumeration order and the byte-identity pin flaked.
    Declared in DETERMINISTIC_ENTRYPOINTS above so HVD009 seeds its
    reachability here and must flag the unsorted walk."""
    rows = []
    for seg in glob.glob(dir_ + "/BENCH_r*.json"):  # EXPECT: HVD009
        rows.append(seg)
    return json.dumps({"rows": rows}, sort_keys=True, indent=1)


def pr8_wire_gate_builder():
    """PR 8 bug #1, jaxpr tier: a traced step whose reduction runs
    over a size-1 mesh axis. Before the r08 wire gate, the monolithic
    leg emitted exactly this for every leaf at world 1 (12 dead
    size-1 all-reduces per transformer step); HVD007's check (a) must
    flag the size-1 reduce. Returns (jitted step, example args,
    mesh axis sizes) for analysis.jaxpr_verify.verify_traced."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(2, 1),
                ("data", "one"))

    def local(g):
        g = lax.psum(g, "data")
        return lax.psum(g, "one")  # size-1 axis: identity wire

    step = jax.jit(shard_map(local, mesh=mesh, in_specs=P(),
                             out_specs=P()))
    args = (jax.ShapeDtypeStruct((4,), jnp.float32),)
    return step, args, {"data": 2, "one": 1}


def pr8_legacy_double_reduce_builder():
    """PR 8 bug #2, jaxpr tier: the legacy-jax psum transpose shape —
    a gradient already psum'd over an axis is psum'd over that same
    axis again, arriving |axis|x too large (measured 2.0x/4.0x per
    tp/sp axis in round 8). HVD007's check (d) must flag the double
    reduction."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("data",))

    def local(g):
        s = lax.psum(g, "data")          # the real reduction
        return lax.psum(s, "data") * 0.5  # the transpose's re-reduce

    step = jax.jit(shard_map(local, mesh=mesh, in_specs=P(),
                             out_specs=P()))
    args = (jax.ShapeDtypeStruct((4,), jnp.float32),)
    return step, args, {"data": 2}


def pr13_flag_rides_compressed_carrier_builder():
    """PR 13, jaxpr tier: the first gradient-compression draft reused
    the dense flag-carrier packing verbatim, so a bucket cast to fp16
    for the wire carried its finite-flag as element n+1 OF THE FP16
    PSUM — the veto count crossed the network in half precision and
    no exact vote existed anywhere. HVD007's check (e) must flag both
    the planned ride and the missing separate f32 vote. Returns
    (jitted step, example args, mesh axis sizes, buggy plan) for
    analysis.jaxpr_verify.verify_traced(..., plan=...,
    numerics_guard=True)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.common.compat import shard_map
    from horovod_tpu.parallel.train import OverlapPlan, WireGroup

    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("data",))

    def local(g, flag):
        # the draft's fused ride: cast, append the flag, one lossy psum
        wire = jnp.concatenate([g.astype(jnp.float16).ravel(),
                                flag.astype(jnp.float16)])
        red = lax.psum(wire, "data")
        return red[:-1].astype(jnp.float32), red[-1]

    step = jax.jit(shard_map(local, mesh=mesh,
                             in_specs=(P(), P()), out_specs=(P(), P())))
    args = (jax.ShapeDtypeStruct((16,), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.float32))
    plan = OverlapPlan(
        threshold=4096, guard=True, n_leaves=1,
        bucket_leaf_indices=((0,),), bucket_raxes=(("data",),),
        bucket_nbytes=(64,),
        wire=((WireGroup("float16", 17, True, None),),),
        digest="1:64|c=fp16", leaf_raxes=(("data",),),
        loose_inexact=(), bucket_compression=("fp16",))
    return step, args, {"data": 2}, plan
