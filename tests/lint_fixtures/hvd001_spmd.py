"""HVD001 fixture: seeded SPMD-divergence positives and negatives.

Lines with a seeded violation carry trailing EXPECT markers naming the
rule id; tests/test_lint.py asserts the analyzer reports exactly those
(rule, line) pairs for this file.
"""

import horovod_tpu as hvd


def direct_conditional(x):
    if hvd.rank() == 0:
        return hvd.allreduce(x)  # EXPECT: HVD001
    return x


def else_branch_is_divergent_too(x):
    if hvd.rank() == 0:
        return x
    else:
        return hvd.allgather(x)  # EXPECT: HVD001


def early_return_guard(x):
    if hvd.rank() != 0:
        return x
    hvd.barrier()  # EXPECT: HVD001
    return x


def variable_taint(x):
    is_root = hvd.rank() == 0
    if is_root:
        hvd.broadcast(x, root_rank=0)  # EXPECT: HVD001
    return x


def size_conditional(x):
    # uniform within one world, but an epoch hazard under elastic
    if hvd.size() > 1:
        return hvd.allreduce(x)  # EXPECT: HVD001
    return x


def _sync_helper(x):
    return hvd.allreduce(x, name="helper")


def one_level_indirection(x):
    if hvd.local_rank() == 0:
        return _sync_helper(x)  # EXPECT: HVD001
    return x


def boolop_shortcircuit():
    hvd.rank() == 0 and hvd.barrier()  # EXPECT: HVD001


# -- negatives: none of these may be reported ------------------------------

def unconditional(x):
    return hvd.allreduce(x)


def loop_variable_named_rank(x):
    # `rank` here is a plain loop variable, not the rank() query
    for rank in range(8):
        if rank == 0:
            x = hvd.allreduce(x)
    return x


def rank_used_outside_condition(x):
    root = hvd.rank()
    hvd.broadcast(x, root_rank=0)
    return root


def guarded_but_suppressed(x):
    if hvd.rank() == 0:
        # hvdlint: disable-next=HVD001 (fixture: justified suppression)
        hvd.barrier()
    return x
