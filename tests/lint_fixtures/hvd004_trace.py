"""HVD004 fixture: python side-effects inside traced functions."""

import os
import time
from functools import partial

import jax
import jax.numpy as jnp

from horovod_tpu import faults
from horovod_tpu.metrics import REGISTRY

_m_steps = REGISTRY.counter("hvdfix_traced_steps_total",
                            "Seeded trace-impurity target.")


@jax.jit
def decorated_wallclock(x):
    t0 = time.perf_counter()  # EXPECT: HVD004
    return x * t0


@partial(jax.jit, static_argnums=0)
def decorated_partial_env(n, x):
    scale = float(os.environ.get("HVDFIX_SCALE", "1"))  # EXPECT: HVD004
    return x * scale * n


@jax.jit
def decorated_metrics(x):
    _m_steps.inc()  # EXPECT: HVD004
    return x + 1


@jax.jit
def decorated_faults(x):
    faults.fire("numerics.grad")  # EXPECT: HVD004
    return x


def _wrapped_by_call(x):
    _m_steps.inc()  # EXPECT: HVD004
    return x * 2


_jitted = jax.jit(_wrapped_by_call)


@jax.jit
def decorated_env_value(x):
    from horovod_tpu.common import config
    scale = config.env_value("HOROVOD_FUSION_THRESHOLD")  # EXPECT: HVD004
    return x * scale


@jax.jit
def effect_after_nested_target(x):
    # the nested traced def is skipped (it has its own pass), but the
    # side-effect AFTER it in the same statement list must still fire
    @jax.jit
    def inner(y):
        return y + 1
    t0 = time.monotonic()  # EXPECT: HVD004
    return inner(x) * t0


@jax.jit
def decorated_span_mutation(x):
    from horovod_tpu import tracing
    tracing.record("dispatch", "fixture_op")  # EXPECT: HVD004
    return x + 1


@jax.jit
def decorated_timeline_span(x):
    tl = _FAKE_TIMELINE
    tl.negotiate_start("fixture_op")  # EXPECT: HVD004
    return x * 2


# -- negatives -------------------------------------------------------------

_FAKE_TIMELINE = None


def span_outside_tracing(x):
    # span emission in plain (untraced) python is the intended use
    from horovod_tpu import tracing
    tracing.record("dispatch", "fixture_ok")
    return x


@jax.jit
def lookalike_record(x):
    # a .record() on a non-tracing receiver (the autotuner's sample
    # sink) is NOT a span mutation
    class _Tuner:
        def record(self, *a):
            return None
    _Tuner().record(1, 2)
    return x

@jax.jit
def pure_kernel(x):
    # functional array update: .at[].set is NOT a metrics mutation
    return x.at[0].set(jnp.sum(x))


def side_effects_outside_tracing(x):
    _m_steps.inc()
    t0 = time.perf_counter()
    return x, t0


def _builder(n):
    # env read in the BUILDER (runs per call, outside tracing) is fine
    mode = os.environ.get("HVDFIX_MODE", "a")

    @jax.jit
    def kernel(x):
        return x * n
    return kernel, mode


@jax.jit
def suppressed_effect(x):
    # hvdlint: disable-next=HVD004 (fixture: deliberate trace-time brand)
    _m_steps.inc()
    return x


# -- profiler-session mutations (profiling.py capture entry points) --------

@jax.jit
def decorated_profiler_capture(x):
    from horovod_tpu import profiling
    with profiling.capture("/tmp/fixture_trace"):  # EXPECT: HVD004
        y = x * 2
    return y


@jax.jit
def decorated_profiler_start(x):
    jax.profiler.start_trace("/tmp/fixture_trace")  # EXPECT: HVD004
    y = x + 1
    jax.profiler.stop_trace()  # EXPECT: HVD004
    return y


def profile_outside_tracing(x):
    # the intended use: the capture wraps the step LOOP, the jitted
    # step runs inside it
    from horovod_tpu import profiling

    @jax.jit
    def kernel(v):
        return v * 2

    with profiling.capture("/tmp/fixture_trace"):
        for _ in range(3):
            x = kernel(x)
    return x


@jax.jit
def lookalike_capture(x):
    # a .capture() on a non-profiling receiver is NOT a session
    # mutation
    class _Sink:
        def capture(self, *a):
            return None
    _Sink().capture(x)
    return x


# -- journal writes (round 11) ---------------------------------------------

@jax.jit
def decorated_journal_write(x):
    from horovod_tpu import journal
    journal.record("commit", step=1)  # EXPECT: HVD004
    return x + 1


@jax.jit
def decorated_journal_event(x):
    j = _FAKE_JOURNAL
    j.event("commit", step=2)  # EXPECT: HVD004
    return x * 2


_FAKE_JOURNAL = None


def journal_outside_tracing(x):
    # journaling from plain (untraced) python is the intended use
    from horovod_tpu import journal
    journal.record("commit", step=3)
    return x


@jax.jit
def lookalike_journal_event(x):
    # .event() on a non-journal receiver (a threading.Event-style
    # signal holder) is NOT a journal write
    class _Signals:
        def event(self, *a, **kw):
            return None
    _Signals().event("ready")
    return x
