"""HVD005 fixture: path-divergent collective schedules and async
handle leaks — seeded positives (EXPECT-anchored) and negatives."""

import contextlib

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops
from jax import lax


# -- positives --------------------------------------------------------------

def except_arm_skip(x):
    try:
        x = preprocess(x)
        x = hvd.allreduce(x)  # EXPECT: HVD005
    except ValueError:
        log("bad batch")
    return x


def suppress_is_an_except_arm(x):
    with contextlib.suppress(KeyError):
        x = hvd.allreduce(x)  # EXPECT: HVD005
    return x


def early_return_between_psums(x, flag):
    y = lax.psum(x, "data")
    if flag:
        return y  # EXPECT: HVD005
    return lax.psum(y * y, "data")


def conditional_break_in_collective_loop(tensors):
    out = []
    for t in tensors:
        if t is None:
            break  # EXPECT: HVD005
        out.append(hvd.allreduce(t))
    return out


def finally_reorders_schedule(x):
    try:
        x = hvd.allreduce(x)
    finally:
        hvd.barrier()  # EXPECT: HVD005
    return x


def abandoned_async_handle(x):
    h = hvd.allreduce_async(x)  # EXPECT: HVD005
    return x


def discarded_async_result(x):
    hvd.allreduce_async(x)  # EXPECT: HVD005
    return x


def drained_on_one_branch_only(x, fast):
    h = hvd.allreduce_async(x)  # EXPECT: HVD005
    if fast:
        return x
    return collective_ops.synchronize(h)


def _helper_submits(x):
    return hvd.allreduce(x, name="staged")


def interprocedural_partial_protocol(x, flag):
    x = _helper_submits(x)
    if flag:
        return x  # EXPECT: HVD005
    return _helper_submits(x * 2)


# -- negatives: none of these may be reported -------------------------------

def uniform_loop(tensors):
    out = []
    for t in tensors:
        out.append(hvd.allreduce(t))
    return out


def guard_before_any_collective(x, ready):
    if not ready:
        return x
    return hvd.allreduce(x)


def handler_reraises(x):
    try:
        return hvd.allreduce(x)
    except ValueError:
        log("propagating")
        raise


def handle_drained_in_finally(x):
    h = hvd.allreduce_async(x)
    try:
        x = postprocess(x)
    finally:
        x = collective_ops.synchronize(h)
    return x


def handle_returned_to_caller(x):
    h = hvd.allreduce_async(x)
    return h


def handle_stored_for_later(x, pending):
    h = hvd.allreduce_async(x)
    pending.append(h)
    return x


def handles_rebound_in_loop_then_drained(tensors):
    out = []
    for t in tensors:
        h = hvd.allreduce_async(t)
        out.append(collective_ops.synchronize(h))
    return out


def suppressed_with_reason(x, flag):
    y = lax.psum(x, "data")
    if flag:
        # hvdlint: disable-next=HVD005 (fixture: flag is a static
        # config constant, identical on every rank)
        return y
    return lax.psum(y + 1, "data")


def preprocess(x):
    return x


def postprocess(x):
    return x


def log(msg):
    return msg
