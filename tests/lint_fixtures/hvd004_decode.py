"""HVD004 fixture: decode-step side-effects inside the jitted
continuous-batching step (round 18).

decoding.py's contract mirrors serving.py's: the `decode.step` /
`kv.page` seam fires, the hvd_decode_* metrics, the per-sequence
journal records (seq_watermark / seq_done) and the step-latency clock
all live in the UNTRACED worker loop around the AOT-compiled decode
step; the step itself (`_toy_step` and any user step_fn) is pure jnp
math over (params, kv, tokens, positions, seeds). The positives are
the tempting wrong version — journaling the watermark or timing the
step from inside the trace, which would bake one trace-time sample
into every compiled rung; the negatives are the engine-loop shape the
subsystem actually uses.
"""

import time

import jax
import jax.numpy as jnp

from horovod_tpu import faults
from horovod_tpu.metrics import REGISTRY

_m_fix_decode_steps = REGISTRY.counter(
    "hvdfix_decode_steps_total",
    "Seeded decode trace-impurity target.")


@jax.jit
def decode_step_counts_steps(params, kv, tokens):
    _m_fix_decode_steps.inc()  # EXPECT: HVD004
    h = params["embed"][tokens]
    return kv, h


@jax.jit
def decode_step_journals_watermark(kv, tokens, positions):
    from horovod_tpu import journal
    journal.record("seq_watermark", sid=0, token=7)  # EXPECT: HVD004
    return kv.at[0].set(0.0), tokens


@jax.jit
def decode_step_times_itself(kv, tokens):
    t0 = time.perf_counter()  # EXPECT: HVD004
    return kv * t0, tokens


@jax.jit
def decode_step_fires_seam(kv, tokens):
    faults.fire("decode.step")  # EXPECT: HVD004
    return kv, tokens + 1


# -- negatives: the engine-loop shape decoding.py actually uses ------------

@jax.jit
def pure_decode_step(params, kv, tokens, positions):
    """The real decode-step shape: masked attention over the KV rung,
    vmapped per-slot writes, counter-based hash sampling — all pure."""
    h = params["embed"][tokens]
    kv2 = jax.vmap(lambda c, p, v: c.at[p].set(v))(kv, positions, h)
    idx = jnp.arange(kv.shape[1], dtype=jnp.int32)
    mask = idx[None, :] <= positions[:, None]
    scores = jnp.einsum("srd,sd->sr", kv2, h)
    att = jax.nn.softmax(jnp.where(mask, scores, -1e30), axis=-1)
    ctx = jnp.einsum("sr,srd->sd", att, kv2)
    logits = (ctx + h) @ params["unembed"]
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return kv2, nxt


def engine_loop_effects_outside_trace(params, kv, tokens, positions):
    # seam fire, step metric, latency clock and watermark journal wrap
    # the compiled step from plain python — the intended split
    faults.fire("decode.step", tag="w0")
    t0 = time.perf_counter()
    kv2, nxt = pure_decode_step(params, kv, tokens, positions)
    _m_fix_decode_steps.inc()
    from horovod_tpu import journal
    journal.record("seq_watermark", sid=0, token=7)
    return kv2, nxt, time.perf_counter() - t0
