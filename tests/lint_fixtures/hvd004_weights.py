"""HVD004 fixture: live weight pipeline journal/metric effects
inside the jitted swap path (round 17).

The weight pipeline's contract is that adoption bookkeeping —
`weights_adopted` / `weights_rejected` journal events, the swap
histogram, the staleness gauge — happens in the UNTRACED worker
fence around the device_put + buffer flip, never inside the jitted
forward or a jitted swap helper. These positives are the tempting
wrong version — journaling the adoption or observing swap latency
from inside a jitted function — which would brand one trace-time
record into the executable per (re)trace; the negatives are the
fence shape serving.py's `_maybe_adopt` actually uses.
"""

import time

import jax
import jax.numpy as jnp

from horovod_tpu import journal
from horovod_tpu.metrics import REGISTRY

_m_fix_swap = REGISTRY.histogram(
    "hvdfix_weights_swap_seconds",
    "Seeded weight-swap trace-impurity target.")
_m_fix_stale = REGISTRY.gauge(
    "hvdfix_weights_staleness_steps",
    "Seeded weight-staleness trace-impurity target.")


@jax.jit
def swap_journals_adoption(params, x):
    journal.record("weights_adopted", digest="d1")  # EXPECT: HVD004
    return x @ params


@jax.jit
def swap_observes_latency(params, x):
    _m_fix_swap.observe(0.002)  # EXPECT: HVD004
    return x @ params


@jax.jit
def swap_stamps_clock(params, x):
    t0 = time.monotonic_ns()  # EXPECT: HVD004
    return x @ params * (t0 % 2)


@jax.jit
def forward_sets_staleness(params, x):
    _m_fix_stale.set(3.0)  # EXPECT: HVD004
    return jnp.tanh(x @ params)


# -- negatives: the between-batches fence shape serving.py uses ------------

@jax.jit
def pure_two_arg_forward(params, x):
    return jnp.tanh(x @ params)


def adopt_effects_outside_trace(params, x):
    # verify + device_put + buffer flip happen in plain python at
    # the fence; the jitted forward only ever sees the swapped-in
    # params as an argument — the intended split
    t0 = time.monotonic_ns()
    live = jax.device_put(params)
    y = pure_two_arg_forward(live, x)
    t1 = time.monotonic_ns()
    _m_fix_swap.observe((t1 - t0) / 1e9)
    _m_fix_stale.set(0.0)
    journal.record("weights_adopted", digest="d2")
    return y
