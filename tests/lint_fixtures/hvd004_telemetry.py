"""HVD004 fixture: telemetry beats inside traced functions (round 20).

telemetry.py's contract is the journal's: the beat seam, the sampling
it may trigger (a metrics-registry snapshot plus a shard write) and
the detector alerts all live in the UNTRACED loops around the
compiled step — the serving batch loop, the decode engine loop, the
elastic commit boundary. The positives are the tempting wrong
version: beating (or arming) the recorder from inside a jitted step,
which would record exactly one phantom sample per retrace and pay a
registry snapshot + fsync'd shard write at trace time. The negatives
are the engine-loop shape the planes actually use: a pure jitted
step with the beat wrapping it from plain python.
"""

import jax
import jax.numpy as jnp

from horovod_tpu import telemetry


@jax.jit
def train_step_beats_inside(params, grads):
    telemetry.beat("commit")  # EXPECT: HVD004
    return jax.tree_util.tree_map(
        lambda p, g: p - 0.1 * g, params, grads)


@jax.jit
def decode_step_beats_per_worker(kv, tokens):
    from horovod_tpu import telemetry as _telemetry
    _telemetry.beat("decode", key="w0")  # EXPECT: HVD004
    return kv.at[0].set(0.0), tokens + 1


@jax.jit
def serving_step_arms_recorder(x):
    telemetry.configure("serving")  # EXPECT: HVD004
    return x * 2.0


# -- negatives: the loop shape the planes actually use ---------------------

@jax.jit
def pure_step(params, grads):
    """The real traced-step shape: pure pytree math, no seams."""
    return jax.tree_util.tree_map(
        lambda p, g: p - 0.1 * g, params, grads)


def commit_loop_beats_outside_trace(params, grads):
    # The intended split: the compiled step is pure; the beat ticks
    # the telemetry plane from plain python at the commit boundary.
    new_params = pure_step(params, grads)
    telemetry.beat("commit")
    return new_params


def engine_loop_beats_per_tick(kv, tokens, wid):
    kv2 = jnp.asarray(kv) * 1.0
    telemetry.beat("decode", key=wid)
    return kv2, tokens
