"""HVD001 fixture: serving-loop dispatch patterns (round 15).

The serving frontend fans batches out across pool members; done with
collectives, the fan-out must be entered by EVERY member uniformly. A
rank-gated dispatch (only the frontend rank enters the collective) is
the classic serving deadlock and must be flagged; the uniform fan-out
below it must stay clean. Same marker contract as the other fixtures:
trailing EXPECT comments name the exact (rule, line) pairs
tests/test_lint.py asserts.
"""

import horovod_tpu as hvd


def rank_gated_batch_dispatch(batch):
    # frontend-style guard: only rank 0 enters the fan-out, every
    # other member never reaches the collective
    if hvd.rank() == 0:
        return hvd.broadcast(batch, root_rank=0)  # EXPECT: HVD001
    return batch


def rank_gated_result_gather(parts):
    if hvd.rank() != 0:
        return parts
    return hvd.allgather(parts)  # EXPECT: HVD001


def _fan_out(batch):
    return hvd.broadcast(batch, root_rank=0)


def size_gated_fanout_helper(batch):
    # uniform within one pool epoch, but an epoch hazard when the
    # pool resizes mid-flight — exactly the serving autoscale case
    if hvd.size() > 1:
        return _fan_out(batch)  # EXPECT: HVD001
    return batch


# -- negatives: none of these may be reported ------------------------------

def uniform_fan_out(batch):
    # every member enters the broadcast + gather pair — the correct
    # collective serving fan-out shape
    shard = hvd.broadcast(batch, root_rank=0)
    return hvd.allgather(shard)


def uniform_batch_loop(batches):
    # dispatch loop over admitted batches: per-batch collectives are
    # fine as long as every member runs the same loop
    out = []
    for b in batches:
        out.append(hvd.allreduce(b, name="serving_fanout"))
    return out
