"""HVD004 fixture: serving request-lifecycle tracing inside the
traced forward (round 16).

The tracing plane's contract is that phase stamps, ring records,
timeline spans, and phase-histogram observations all happen in the
UNTRACED dispatch/completion path around the AOT-compiled forward.
These positives are the tempting wrong version — stamping phases
from inside the forward itself — which would brand one trace-time
stamp into the executable per (re)trace; the negatives are the
completion-path shape serving.py actually uses.
"""

import time

import jax
import jax.numpy as jnp

from horovod_tpu import tracing
from horovod_tpu.metrics import REGISTRY
from horovod_tpu.timeline import Timeline

_m_fix_phase = REGISTRY.histogram(
    "hvdfix_serving_phase_seconds",
    "Seeded serving trace-impurity target.")


@jax.jit
def forward_observes_phase(x):
    _m_fix_phase.observe(0.001)  # EXPECT: HVD004
    return jnp.tanh(x)


@jax.jit
def forward_stamps_clock(x):
    t0 = time.monotonic_ns()  # EXPECT: HVD004
    return x * (t0 % 2)


@jax.jit
def forward_records_ring(x):
    tracing.record("serving_exec", "b1")  # EXPECT: HVD004
    return x * 2


def forward_spans_timeline(tl: Timeline):
    @jax.jit
    def fwd(x):
        tl.span("req/r1", "COMPUTE", 0, 1)  # EXPECT: HVD004
        return x + 1
    return fwd


# -- negatives: the completion-path shape serving.py actually uses ---------

@jax.jit
def pure_forward(x):
    return jnp.tanh(x)


def complete_batch_effects_outside_trace(x, tl: Timeline):
    # stamps, ring record, phase observation and timeline span wrap
    # the compiled forward from plain python — the intended split
    t0 = time.monotonic_ns()
    tracing.record("serving_exec", "b2")
    y = pure_forward(x)
    t1 = time.monotonic_ns()
    _m_fix_phase.observe((t1 - t0) / 1e9)
    tl.span("req/r2", "COMPUTE", t0, t1)
    return y
