"""Worker for the 2-proc steady-state composed timeline artifact
(VERDICT r05 "What's missing" 1 / weak 3): real XLA train-step
dispatch per step PLUS a real cross-process negotiated collective per
step, with per-rank timelines recording NEGOTIATE spans whose
coordinator-measured latency must sit below the 5 ms cycle budget in
steady state (step 0 — the XLA compile cycle — is excluded from the
claim, marked via the step arg on every span).

The XLA dispatch runs on each rank's OWN 8-virtual-device mesh (the
same honest arrangement as benchmarks/TIMELINE_overlap_2proc_r06.json:
this container's jaxlib CPU backend cannot run cross-process
computations, so the data plane is local while the control plane —
negotiation over TCP through the native C++ coordinator, clock
calibration, per-rank timelines, the merge — is the real
multi-process path; the committed artifact records this mode)."""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device"
                             "_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import tracing  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402
from horovod_tpu.parallel import build_train_step  # noqa: E402
from horovod_tpu.parallel.mesh import data_parallel_mesh  # noqa: E402
from horovod_tpu.timeline import Timeline  # noqa: E402

STEPS = 10  # step 0 is the compile cycle, excluded from the claim


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n
    mesh = data_parallel_mesh(jax.local_devices())

    def loss_fn(params, batch):
        h = jnp.tanh(batch[:, None] * params["w1"][None, :])
        return jnp.mean((h @ params["w2"]) ** 2)

    params = {"w1": jnp.arange(64.0) / 64.0,
              "w2": jnp.ones((64, 32)) * 0.1}
    opt = optax.sgd(0.01)
    opt_state = opt.init(params)
    step_fn = build_train_step(loss_fn, opt, mesh, donate=False)

    from jax.sharding import NamedSharding, PartitionSpec as P
    batch = jax.device_put(
        jnp.asarray(np.arange(16.0, dtype=np.float32)),
        NamedSharding(mesh, P("data")))
    jax.block_until_ready(batch)

    tl = state().timeline
    assert tl is not None, "worker needs HOROVOD_TIMELINE set"
    ctl = state().engine.controller
    assert ctl is not None

    for s in range(STEPS):
        tracing.set_step(s)
        t0 = time.monotonic_ns()
        params, opt_state, metrics = step_fn(params, opt_state, batch)
        jax.block_until_ready(metrics["loss"])
        # One negotiated cross-process collective per step: a generic
        # entry carrying per-rank metadata through the real TCP
        # control plane (submit -> coordinator agreement -> dispatch),
        # recording NEGOTIATE lanes on every rank's timeline.
        h = ctl.submit_generic(f"steady_sync_{s}", 4,
                               lambda metas: metas, meta=str(r))
        got = hvd.synchronize(h.id)
        assert got == [str(i) for i in range(n)], got
        # STEP envelope span (args carry the step id so the merge and
        # the stats can exclude the compile cycle).
        tl.span("train", "STEP", t0, time.monotonic_ns(),
                args={"step": s, "compile": s == 0})

    path = Timeline.rank_path(os.environ["HOROVOD_TIMELINE"], r)
    hvd.shutdown()
    assert os.path.exists(path), path
    print(f"STEADY WORKER OK rank={r} steps={STEPS}", flush=True)


main()
