"""The torch frontend binding: `import horovod_tpu.torch as hvd`
(reference: horovod/torch — mpi_ops.py surface, optimizer.py hooks,
functions.py state_dict helpers). Single-process semantics here; the
real 2-proc run is TestTorchRealLaunch via the launcher."""

import copy
import os
import subprocess
import sys

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def hvd_init():
    hvd.init()
    yield
    hvd.shutdown()


class TestTensorOps:
    def test_allreduce_dtype_preserved(self, hvd_init):
        for dt in [torch.float32, torch.float16, torch.bfloat16]:
            out = hvd.allreduce(torch.ones(4, dtype=dt), op=hvd.Sum,
                                name=f"dt.{dt}")
            assert out.dtype == dt
            np.testing.assert_allclose(out.float().numpy(), 1.0)

    def test_allreduce_inplace_mutates(self, hvd_init):
        t = torch.full((3,), 2.0)
        ret = hvd.allreduce_(t, op=hvd.Sum, name="inp")
        assert ret is t
        np.testing.assert_allclose(t.numpy(), 2.0)

    def test_grouped_allreduce(self, hvd_init):
        outs = hvd.grouped_allreduce(
            [torch.ones(2), torch.ones(3, dtype=torch.float16)],
            name="grp")
        assert outs[0].dtype == torch.float32
        assert outs[1].dtype == torch.float16

    def test_broadcast_allgather_reducescatter(self, hvd_init):
        t = torch.arange(4.0)
        hvd.broadcast_(t, root_rank=0, name="bc")
        g = hvd.allgather(torch.ones(2, 3), name="ag")
        assert g.shape == (2, 3)
        rs = hvd.reducescatter(torch.ones(2, 3), op=hvd.Sum, name="rs")
        assert rs.shape == (2, 3)

    def test_grouped_allgather_and_reducescatter(self, hvd_init):
        outs = hvd.grouped_allgather(
            [torch.ones(2, 3), torch.arange(4.0)], name="gag")
        assert [o.shape for o in outs] == [(2, 3), (4,)]
        outs = hvd.grouped_reducescatter(
            [torch.ones(4, 2), torch.full((2,), 3.0)], op=hvd.Sum,
            name="grs", prescale_factor=2.0)
        assert len(outs) == 2 and outs[0].shape == (4, 2)
        np.testing.assert_allclose(outs[1].numpy(), 6.0)
        # double-synchronize on a composite handle must keep
        # returning TORCH tensors (the meta rides the handle object)
        h = hvd.grouped_allgather_async([torch.ones(2)], name="gag2")
        first = hvd.synchronize(h)
        again = hvd.synchronize(h)
        assert isinstance(again[0], torch.Tensor)
        np.testing.assert_allclose(again[0].numpy(), first[0].numpy())

    def test_alltoall_matches_reference_shapes(self, hvd_init):
        out = hvd.alltoall(torch.arange(4.0), name="a2a")
        assert isinstance(out, torch.Tensor)   # splits-less: bare out
        out, recv = hvd.alltoall(torch.arange(4.0)[:, None],
                                 splits=[4], name="a2av")
        assert recv.tolist() == [4]

    def test_inplace_on_requires_grad_parameter(self, hvd_init):
        """broadcast_parameters(model.named_parameters()) — the
        reference-standard form — writes into requires-grad LEAF
        tensors; the write-back must run under no_grad."""
        torch.manual_seed(7)
        model = torch.nn.Linear(3, 2)
        hvd.broadcast_parameters(model.named_parameters(), root_rank=0)
        p = next(model.parameters())
        assert p.requires_grad
        hvd.allreduce_(p, name="inp.param")   # direct in-place too

    def test_stale_handle_meta_cleared_across_reinit(self):
        """An abandoned async handle's metadata must not resolve
        against the recycled handle id of the NEXT session (engine
        ids restart at 1), which would write into a dead tensor."""
        hvd.init()
        dead = torch.zeros(4)
        hvd.allreduce_async_(dead, op=hvd.Sum, name="abandoned")
        hvd.shutdown()
        hvd.init()
        try:
            h = hvd.allreduce_async(torch.ones(2), op=hvd.Sum,
                                    name="fresh")
            out = hvd.synchronize(h)
            assert out.shape == (2,)   # not the stale 4-elem write
            np.testing.assert_allclose(out.numpy(), 1.0)
            np.testing.assert_allclose(dead.numpy(), 0.0)
        finally:
            hvd.shutdown()

    def test_unsynchronized_handle_meta_released_with_engine_handle(
            self, hvd_init):
        """A never-torch-synchronized async handle's metadata dies
        when the ENGINE releases the handle (e.g. the raw
        collective_ops synchronize path), not at session end — the
        r05 leak: torch meta entries accumulated for the whole
        session when callers synchronized through the non-torch
        API."""
        from horovod_tpu.ops import collective_ops as C
        from horovod_tpu.torch import _handle_meta
        h = hvd.allreduce_async(torch.ones(3), op=hvd.Sum,
                                name="engine-released")
        assert h in _handle_meta
        # Engine-side release without torch.synchronize ever running.
        C.synchronize(h)
        assert h not in _handle_meta

    def test_composite_handle_rejected_across_reinit(self):
        """A grouped handle held across shutdown+init must refuse to
        synchronize (its child ids would resolve against the new
        engine's recycled ids)."""
        hvd.init()
        h = hvd.grouped_allgather_async([torch.ones(2)], name="xsess")
        hvd.synchronize(h)
        hvd.shutdown()
        hvd.init()
        try:
            with pytest.raises(RuntimeError, match="previous"):
                hvd.synchronize(h)
        finally:
            hvd.shutdown()

    def test_async_handle_protocol(self, hvd_init):
        h = hvd.allreduce_async(torch.ones(4), name="h0")
        out = hvd.synchronize(h)
        assert isinstance(out, torch.Tensor)

    def test_sparse_allreduce_coo(self, hvd_init):
        s = torch.sparse_coo_tensor(torch.tensor([[1, 4, 1]]),
                                    torch.ones(3, 2), size=(6, 2))
        d = hvd.sparse_allreduce(s, op=hvd.Sum, name="sp").to_dense()
        assert float(d[1, 0]) == 2.0 and float(d[4, 0]) == 1.0

    def test_rejects_dense_in_sparse_and_noncpu_guard(self, hvd_init):
        with pytest.raises(TypeError):
            hvd.sparse_allreduce(torch.ones(3))
        with pytest.raises(TypeError):
            hvd.allreduce(np.ones(3), name="np")


class TestDistributedOptimizer:
    def _fit(self, opt_factory, steps=150):
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1, bias=False)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = opt_factory(model)
        X = torch.randn(64, 4)
        Y = X @ torch.randn(4, 1)
        loss = None
        for _ in range(steps):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X), Y)
            loss.backward()
            opt.step()
        return float(loss.detach()), model

    def test_hook_optimizer_converges(self, hvd_init):
        loss, _ = self._fit(lambda m: hvd.DistributedOptimizer(
            torch.optim.SGD(m.parameters(), lr=0.1),
            named_parameters=m.named_parameters()))
        assert loss < 1e-4, loss

    def test_unnamed_parameters_autoname(self, hvd_init):
        loss, _ = self._fit(lambda m: hvd.DistributedOptimizer(
            torch.optim.SGD(m.parameters(), lr=0.1)))
        assert loss < 1e-4, loss

    def test_backward_passes_per_step_averages(self, hvd_init):
        """k accumulation passes then one step must equal one step on
        the averaged gradient (the LocalGradientAggregationHelper
        contract)."""
        torch.manual_seed(1)
        X = torch.randn(6, 3)
        Y = torch.randn(6, 1)

        def run(k):
            torch.manual_seed(2)
            model = torch.nn.Linear(3, 1, bias=False)
            opt = hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=1.0),
                named_parameters=model.named_parameters(),
                backward_passes_per_step=k)
            opt.zero_grad()
            for i in range(k):
                loss = torch.nn.functional.mse_loss(
                    model(X), Y)
                loss.backward()
            opt.step()
            return model.weight.detach().clone()

        w2 = run(2)
        # manual: same two backwards accumulate, grad/2 applied
        torch.manual_seed(2)
        model = torch.nn.Linear(3, 1, bias=False)
        for i in range(2):
            torch.nn.functional.mse_loss(model(X), Y).backward()
        with torch.no_grad():
            want = model.weight - 1.0 * model.weight.grad / 2
        np.testing.assert_allclose(w2.numpy(), want.numpy(), rtol=1e-6)

    def test_manual_synchronize_and_skip(self, hvd_init):
        torch.manual_seed(3)
        model = torch.nn.Linear(3, 1)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        opt.zero_grad()
        torch.nn.functional.mse_loss(
            model(torch.randn(4, 3)), torch.randn(4, 1)).backward()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()

    def test_zero_grad_with_inflight_raises(self, hvd_init):
        torch.manual_seed(4)
        model = torch.nn.Linear(3, 1)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        torch.nn.functional.mse_loss(
            model(torch.randn(4, 3)), torch.randn(4, 1)).backward()
        with pytest.raises(RuntimeError, match="in flight"):
            opt.zero_grad()
        opt.synchronize()

    def test_duplicate_names_rejected(self, hvd_init):
        model = torch.nn.Linear(3, 1, bias=False)
        with pytest.raises(ValueError, match="unique"):
            hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=[("w", model.weight),
                                  ("w", model.weight)])

    def test_synchronize_drains_all_handles_on_error(self, hvd_init):
        """One failed reduction must not wedge the optimizer: every
        other handle still applies, state clears, zero_grad works,
        and the original error surfaces."""
        torch.manual_seed(6)
        model = torch.nn.Linear(3, 1)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        torch.nn.functional.mse_loss(
            model(torch.randn(4, 3)), torch.randn(4, 1)).backward()
        opt._handles[999999999] = (None, 999999999)  # dead handle id
        with pytest.raises(KeyError):
            opt.synchronize()
        assert not opt._handles
        opt.zero_grad()   # must not raise "in flight"

    def test_broadcast_optimizer_state_roundtrip(self, hvd_init):
        torch.manual_seed(5)
        model = torch.nn.Linear(3, 1)
        opt = hvd.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters())
        opt.zero_grad()
        torch.nn.functional.mse_loss(
            model(torch.randn(4, 3)), torch.randn(4, 1)).backward()
        opt.step()   # materialize Adam state (exp_avg etc.)
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        sd = opt.state_dict()
        assert any("exp_avg" in str(k2)
                   for st in sd["state"].values() for k2 in st)


class TestSyncBatchNorm:
    def test_size1_matches_vanilla(self, hvd_init):
        """World size 1: must behave exactly like torch BatchNorm
        (train and eval, stats tracked)."""
        torch.manual_seed(9)
        x = torch.randn(8, 3, 5)
        bn = hvd.SyncBatchNorm(3, momentum=0.3)
        ref = torch.nn.BatchNorm1d(3, momentum=0.3)
        np.testing.assert_allclose(bn(x).detach().numpy(),
                                   ref(x).detach().numpy(), atol=1e-6)
        np.testing.assert_allclose(bn.running_var.numpy(),
                                   ref.running_var.numpy(), atol=1e-6)
        bn.eval(), ref.eval()
        np.testing.assert_allclose(bn(x).detach().numpy(),
                                   ref(x).detach().numpy(), atol=1e-6)

    def test_local_mode_edge_parity(self, hvd_init):
        """The world-size-1 fallback must match torch BatchNorm on
        the edges: no running stats in eval, momentum=None cumulative
        averaging, num_batches_tracked counting."""
        torch.manual_seed(10)
        x = torch.randn(6, 3)
        # track_running_stats=False + eval: batch stats, no crash
        bn = hvd.SyncBatchNorm(3, track_running_stats=False)
        ref = torch.nn.BatchNorm1d(3, track_running_stats=False)
        bn.eval(), ref.eval()
        np.testing.assert_allclose(bn(x).detach().numpy(),
                                   ref(x).detach().numpy(), atol=1e-6)
        # momentum=None: cumulative moving average semantics
        bn = hvd.SyncBatchNorm(3, momentum=None)
        ref = torch.nn.BatchNorm1d(3, momentum=None)
        for _ in range(3):
            bn(x), ref(x)
        np.testing.assert_allclose(bn.running_var.numpy(),
                                   ref.running_var.numpy(), atol=1e-6)
        assert int(bn.num_batches_tracked) == 3
        bn.eval(), ref.eval()
        np.testing.assert_allclose(bn(x).detach().numpy(),
                                   ref(x).detach().numpy(), atol=1e-6)

    def test_convert_recursive(self, hvd_init):
        m = torch.nn.Sequential(
            torch.nn.Conv2d(3, 4, 1), torch.nn.BatchNorm2d(4),
            torch.nn.Sequential(torch.nn.BatchNorm2d(4)))
        with torch.no_grad():
            m[1].running_mean.fill_(0.5)
        c = hvd.SyncBatchNorm.convert_sync_batchnorm(m)
        assert isinstance(c[1], hvd.SyncBatchNorm)
        assert isinstance(c[2][0], hvd.SyncBatchNorm)
        np.testing.assert_allclose(c[1].running_mean.numpy(), 0.5)


class TestTorchElastic:
    def test_torch_state_commit_restore(self, hvd_init):
        """hvd.elastic.TorchState commit/restore semantics
        (reference: horovod/torch/elastic TorchState)."""
        torch.manual_seed(8)
        model = torch.nn.Linear(3, 2)
        opt = torch.optim.Adam(model.parameters(), lr=0.01)
        state = hvd.elastic.TorchState(model, opt, batch=5)
        torch.nn.functional.mse_loss(
            model(torch.randn(4, 3)), torch.randn(4, 2)).backward()
        opt.step()
        state.batch = 9
        state.commit()
        committed = copy.deepcopy(model.state_dict())
        # diverge, then roll back
        with torch.no_grad():
            model.weight.add_(1.0)
        state.batch = 11
        state.restore()
        for k, v in model.state_dict().items():
            np.testing.assert_allclose(v.numpy(), committed[k].numpy())
        assert state.batch == 9   # restored to last commit
        assert "exp_avg" in str(opt.state_dict()["state"])

    def test_torch_state_sync_single(self, hvd_init):
        model = torch.nn.Linear(2, 2)
        state = hvd.elastic.TorchState(
            model, torch.optim.SGD(model.parameters(), lr=0.1),
            epoch=3)
        state.sync()   # world size 1: a no-op broadcast, must not err
        assert state.epoch == 3


class TestDynamicSubclass:
    """The DistributedOptimizer factory builds a dynamic subclass of
    the wrapped optimizer's class (the reference's pattern), so every
    isinstance-gated torch integration works on the wrapper."""

    def _opt(self, model, **kw):
        return hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), **kw)

    def test_isinstance_and_class_name(self, hvd_init):
        model = torch.nn.Linear(4, 1, bias=False)
        opt = self._opt(model)
        assert isinstance(opt, torch.optim.Optimizer)
        assert isinstance(opt, torch.optim.SGD)
        assert type(opt).__name__ == "DistributedSGD"

    def test_double_wrap_rejected(self, hvd_init):
        model = torch.nn.Linear(4, 1, bias=False)
        opt = self._opt(model)
        with pytest.raises(ValueError, match="already"):
            hvd.DistributedOptimizer(opt)

    def test_lr_scheduler_works(self, hvd_init):
        """The headline unblocked integration: lr_scheduler.__init__
        raises TypeError for non-Optimizers, so this line is the
        isinstance contract, end to end."""
        model = torch.nn.Linear(4, 1, bias=False)
        opt = self._opt(model)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1,
                                                gamma=0.5)
        model(torch.randn(8, 4)).pow(2).mean().backward()
        opt.step()
        sched.step()
        assert opt.param_groups[0]["lr"] == pytest.approx(0.05)

    def _scaler(self, init_scale):
        try:
            sc = torch.amp.GradScaler("cpu", init_scale=init_scale,
                                      enabled=True)
        except (RuntimeError, TypeError) as e:  # pragma: no cover
            pytest.skip(f"no CPU GradScaler in this torch: {e}")
        if not sc.is_enabled():  # pragma: no cover
            pytest.skip("CPU GradScaler disabled in this torch")
        return sc

    def test_gradscaler_interop_applies_when_finite(self, hvd_init):
        """The documented AMP pattern (reference:
        horovod/torch/optimizer.py GradScaler docs): scale ->
        backward -> synchronize -> unscale_ -> skip_synchronize +
        scaler.step -> update. found_inf runs over the REDUCED grads,
        so every rank reaches the same decision."""
        torch.manual_seed(11)
        model = torch.nn.Linear(4, 1, bias=False)
        opt = self._opt(model)
        scaler = self._scaler(1024.0)
        loss = model(torch.randn(8, 4)).pow(2).mean()
        scaler.scale(loss).backward()
        opt.synchronize()
        scaler.unscale_(opt)
        before = model.weight.detach().clone()
        with opt.skip_synchronize():
            scaler.step(opt)
        scaler.update()
        assert not torch.equal(before, model.weight)
        assert scaler.get_scale() == 1024.0   # clean step: no backoff

    def test_gradscaler_overflow_skips_and_backs_off(self, hvd_init):
        torch.manual_seed(12)
        model = torch.nn.Linear(4, 1, bias=False)
        opt = self._opt(model)
        scaler = self._scaler(1024.0)
        loss = model(torch.randn(8, 4)).pow(2).mean()
        scaler.scale(loss).backward()
        opt.synchronize()
        for p in model.parameters():
            p.grad.fill_(float("inf"))   # post-reduction overflow
        scaler.unscale_(opt)
        before = model.weight.detach().clone()
        with opt.skip_synchronize():
            scaler.step(opt)
        scaler.update()
        assert torch.equal(before, model.weight)   # step skipped
        assert scaler.get_scale() == 512.0         # backoff 0.5x


class Test64BitBridge:
    """int64/float64 on the 32-bit numpy bridge: per-dtype-per-op
    warnings, and a hard error when int64 VALUES cannot round-trip
    through int32 (truncation is corruption, not precision loss)."""

    @pytest.fixture()
    def x64_off(self):
        import jax
        prev = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", False)
        from horovod_tpu import torch as hvt
        hvt._warned_64bit.clear()
        yield
        jax.config.update("jax_enable_x64", prev)

    def test_int64_out_of_range_raises(self, hvd_init, x64_off):
        with pytest.raises(ValueError, match="int32 range"):
            hvd.allreduce(torch.tensor([2 ** 40]), op=hvd.Sum,
                          name="big64")
        with pytest.raises(ValueError, match="int32 range"):
            hvd.broadcast(torch.tensor([-2 ** 33]), root_rank=0,
                          name="neg64")

    def test_int64_sum_headroom_catches_reduction_wrap(self, hvd_init,
                                                       x64_off):
        """In-range int64 inputs can still WRAP during an int32 Sum;
        the submit check scales the bound by the reducing-set size."""
        from horovod_tpu import torch as hvt
        t = torch.tensor([2 ** 30])   # fits int32 locally
        hvt._to_jax(t, "allreduce", sum_headroom=1)   # local ok
        with pytest.raises(ValueError, match="Sum over all members"):
            hvt._to_jax(t, "allreduce", sum_headroom=4)
        # world size 1: headroom collapses to 1 for Sum and avg=False
        assert hvt._sum_headroom(hvd.Sum) == 1
        assert hvt._sum_headroom(None, average=False) == 1
        assert hvt._sum_headroom(None) == 1

    def test_int64_in_range_still_reduces(self, hvd_init, x64_off):
        out = hvd.allreduce(torch.tensor([5, -7]), op=hvd.Sum,
                            name="small64")
        assert out.dtype == torch.int64
        np.testing.assert_array_equal(out.numpy(), [5, -7])

    def test_warning_is_per_dtype_per_op(self, hvd_init, x64_off):
        from horovod_tpu import torch as hvt
        hvd.allreduce(torch.tensor([1]), op=hvd.Sum, name="w1")
        hvd.allreduce(torch.tensor([2]), op=hvd.Sum, name="w2")
        assert ("torch.int64", "allreduce") in hvt._warned_64bit
        assert len([k for k in hvt._warned_64bit
                    if k[0] == "torch.int64"]) == 1
        hvd.broadcast(torch.tensor([3]), root_rank=0, name="w3")
        assert ("torch.int64", "broadcast") in hvt._warned_64bit
        hvd.allreduce(torch.tensor([1.0], dtype=torch.float64),
                      name="w4")
        assert ("torch.float64", "allreduce") in hvt._warned_64bit


class TestSyncBatchNormNames:
    def test_explicit_name_and_channel_fold(self, hvd_init):
        bn = hvd.SyncBatchNorm(6, name="encoder.bn1")
        assert bn._bn_uid == "encoder.bn1.c6"
        # ordinal fallback still folds the channel count, so same-
        # ordinal-different-width construction cannot silently pair
        auto = hvd.SyncBatchNorm(3)
        assert auto._bn_uid.startswith("sync_bn.")
        assert auto._bn_uid.endswith(".c3")

    def test_convert_uses_module_paths_with_prefix(self, hvd_init):
        model = torch.nn.Sequential(
            torch.nn.Conv2d(2, 4, 1), torch.nn.BatchNorm2d(4),
            torch.nn.Sequential(torch.nn.BatchNorm2d(4)))
        conv = hvd.SyncBatchNorm.convert_sync_batchnorm(
            model, name_prefix="net")
        assert conv[1]._bn_uid == "net.1.c4"
        assert conv[2][0]._bn_uid == "net.2.0.c4"
        # without a prefix: back-compat construction ordinals
        model2 = torch.nn.Sequential(torch.nn.BatchNorm2d(4))
        conv2 = hvd.SyncBatchNorm.convert_sync_batchnorm(model2)
        assert conv2[0]._bn_uid.startswith("sync_bn.")

    def test_converted_model_still_trains(self, hvd_init):
        torch.manual_seed(13)
        model = torch.nn.Sequential(
            torch.nn.Conv2d(2, 4, 1), torch.nn.BatchNorm2d(4))
        conv = hvd.SyncBatchNorm.convert_sync_batchnorm(
            model, name_prefix="m")
        y = conv(torch.randn(3, 2, 5, 5))
        y.pow(2).mean().backward()
        assert conv[1].weight.grad is not None


@pytest.mark.integration
class TestTorchRealLaunch:
    def test_two_process_torch_frontend(self):
        from tests.test_runner import run_launcher
        r = run_launcher(2, os.path.join("tests", "mp_worker_torch.py"),
                         timeout=360)
        if r.returncode != 0 and "Multiprocess computations aren't " \
                "implemented" in (r.stdout + r.stderr):
            # same capability gate as test_chaos.py / test_numerics.py
            pytest.skip("this jaxlib's CPU backend cannot run "
                        "cross-process collectives")
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("TORCH FRONTEND ALL OK") == 2, r.stdout
