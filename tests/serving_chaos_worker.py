"""Serving-chaos pool member: launched per-rank by the elastic runner
(the probe-gated 2-rank chaos leg in tests/test_serving.py), it joins
the ServingFrontend living in the LAUNCHING test process over the
HMAC-signed control-plane wire and serves batches until the frontend
says stop.

Deliberately CONTROL-PLANE ONLY, like tests/journal_chaos_worker.py:
data-parallel inference runs a full forward replica per member — there
is no cross-member collective — so the whole serving lifecycle
(rendezvous, pool join, batch pull/push, the seeded mid-batch crash,
the gang restart, the rejoin) exercises on jaxlib builds whose CPU
backend cannot run cross-process collectives. The frontend outlives
the gang restart (it is not under the runner), which is exactly the
serving deployment shape: the driver-side frontend survives worker
churn and its retry accounting is what proves zero dropped requests.

Env contract (set by the test): SERVING_TEST_ADDR / SERVING_TEST_PORT
(the frontend endpoint), SERVING_TEST_SECRET (the endpoint's HMAC key
— distinct from the runner's own HOROVOD_SECRET), SERVING_TEST_DMODEL.
The seeded fault (HOROVOD_FAULTS=serving.batch:crash:...) arms from
env inside hvd.init() and fires mid-batch inside remote_worker_loop.
With SERVING_TEST_WEIGHTS_DIR set the member serves the two-arg
live-weight forward (bootstrap params deterministic from DMODEL, so
the launching frontend derives the identical tree) and hot-swaps from
that pipeline directory between pulls — a seeded
weights.adopt:crash is then a REAL process death mid-swap.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import serving  # noqa: E402

D = int(os.environ.get("SERVING_TEST_DMODEL", "8"))
WEIGHTS_DIR = os.environ.get("SERVING_TEST_WEIGHTS_DIR", "")


def forward(x):
    return jnp.tanh(x) * 2.0


def forward_weighted(params, x):
    return jnp.tanh(x @ params["w"]) + params["b"]


def bootstrap_params():
    # Deterministic in D: the launching test builds the same tree so
    # the structure digests agree across the wire.
    return {"w": jnp.eye(D), "b": jnp.zeros((D,))}


def main():
    standalone = os.environ.get("SERVING_TEST_STANDALONE") == "1"
    if standalone:
        # Plain-subprocess mode (the ungated kill test): no launcher,
        # so arm the seeded faults from env ourselves.
        from horovod_tpu import faults
        faults.configure_from_env()
        wid = os.environ.get("SERVING_TEST_WID",
                             f"pid{os.getpid()}")
    else:
        hvd.init()
        wid = f"rank{hvd.rank()}-pid{os.getpid()}"
    if WEIGHTS_DIR:
        n = serving.remote_worker_loop(
            os.environ["SERVING_TEST_ADDR"],
            int(os.environ["SERVING_TEST_PORT"]),
            forward_weighted, (D,), wid=wid,
            secret=os.environ.get("SERVING_TEST_SECRET", ""),
            params=bootstrap_params(), weights_dir=WEIGHTS_DIR)
    else:
        n = serving.remote_worker_loop(
            os.environ["SERVING_TEST_ADDR"],
            int(os.environ["SERVING_TEST_PORT"]),
            forward, (D,), wid=wid,
            secret=os.environ.get("SERVING_TEST_SECRET", ""))
    print(f"serving worker {wid}: served {n} batches", flush=True)
    if not standalone:
        hvd.shutdown()


if __name__ == "__main__":
    main()
