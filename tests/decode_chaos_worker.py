"""Decode-chaos pool member: a plain-subprocess remote decode worker
for the mid-SEQUENCE kill tests in tests/test_decoding.py and the
bench chaos leg (bench.py --serving decode leg).

It joins the DecodeFrontend living in the LAUNCHING process over the
HMAC-signed lease/emit wire (decoding.remote_decode_loop) and decodes
until the frontend says stop. A seeded HOROVOD_FAULTS=
decode.step:crash:... arms from env and is a REAL os._exit(43)
mid-sequence — the process dies with its KV cache and partially
emitted streams, which is exactly what the per-sequence watermark
resume has to survive.

Env contract (set by the launcher): DECODE_TEST_ADDR /
DECODE_TEST_PORT (the frontend endpoint), DECODE_TEST_SECRET (the
endpoint's HMAC key), DECODE_TEST_WID (worker id; defaults to the
pid). The toy LM is the decoding module's default, deterministic in
its seed, so the frontend-side uninterrupted baseline is bitwise
comparable.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu import decoding, faults  # noqa: E402


def main():
    faults.configure_from_env()
    wid = os.environ.get("DECODE_TEST_WID", f"pid{os.getpid()}")
    n = decoding.remote_decode_loop(
        os.environ["DECODE_TEST_ADDR"],
        int(os.environ["DECODE_TEST_PORT"]),
        wid=wid,
        secret=os.environ.get("DECODE_TEST_SECRET", ""))
    print(f"decode worker {wid}: finished {n} sequences", flush=True)


if __name__ == "__main__":
    main()
