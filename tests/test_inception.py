"""Inception V3: the lead model of the reference's benchmark table
(reference: docs/benchmarks.rst — Inception V3 ~90% scaling at 128
GPUs)."""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import create_inception_v3, init_inception


def test_inception_v3_param_count_and_forward():
    model = create_inception_v3(dtype=jnp.float32)
    variables = init_inception(model, jax.random.PRNGKey(0), 299)
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    # Canonical Inception V3 without the aux head, TF-slim BN
    # convention (no gamma): torchvision's 23,834,568 minus the
    # 17,216 BN scale params.
    assert n == 23_817_352, n

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 299, 299, 3))
    logits, updates = model.apply(variables, x, train=True,
                                  mutable=["batch_stats"])
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in updates


def test_inception_v3_train_step_reduces_loss():
    import optax
    model = create_inception_v3(num_classes=10, dtype=jnp.float32)
    variables = init_inception(model, jax.random.PRNGKey(0), 128)
    params, stats = variables["params"], variables["batch_stats"]
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128, 3))
    y = jnp.array([0, 1])

    def loss_fn(p, stats):
        logits, upd = model.apply(
            {"params": p, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, 10)
        loss = jnp.mean(-jnp.sum(
            onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, upd["batch_stats"]

    opt = optax.sgd(0.01)
    state = opt.init(params)
    step = jax.jit(lambda p, s, st: _step(p, s, st))

    def _step(p, s, st):
        (loss, s2), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, s)
        updates, st2 = opt.update(grads, st, p)
        return optax.apply_updates(p, updates), s2, st2, loss

    losses = []
    for _ in range(2):
        params, stats, state, loss = step(params, stats, state)
        losses.append(float(loss))
    # one step on the fixed batch reduces its loss (tiny-batch SGD
    # oscillates over longer horizons — not what this asserts)
    assert losses[1] < losses[0], losses


def test_stem_space_to_depth_equivalence():
    """The s2d stem transform (models/inception.py stem_s2d): a
    stride-2 3x3 VALID conv on (H,W,3) equals a stride-1 2x2 VALID
    conv on the 2x2 space-to-depth input when the canonical kernel is
    embedded in the packed one (extra taps zero) — the MLPerf-style
    conv0 transform, verified tap-for-tap."""
    from jax import lax
    rng = np.random.RandomState(0)
    H = W = 11  # odd, like 299
    x = jnp.asarray(rng.randn(2, H, W, 3).astype(np.float32))
    k3 = jnp.asarray(rng.randn(3, 3, 3, 8).astype(np.float32))

    want = lax.conv_general_dilated(
        x, k3, window_strides=(2, 2), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

    xp = jnp.pad(x, ((0, 0), (0, H % 2), (0, W % 2), (0, 0)))
    b, h2, w2, c = xp.shape
    z = xp.reshape(b, h2 // 2, 2, w2 // 2, 2, c)
    z = z.transpose(0, 1, 3, 2, 4, 5).reshape(b, h2 // 2, w2 // 2,
                                              4 * c)
    k2 = np.zeros((2, 2, 4 * 3, 8), np.float32)
    for di in range(3):
        for dj in range(3):
            u, r = di // 2, di % 2
            v, s = dj // 2, dj % 2
            for ch in range(3):
                k2[u, v, (2 * r + s) * 3 + ch] = k3[di, dj, ch]
    got = lax.conv_general_dilated(
        z, jnp.asarray(k2), window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_stem_s2d_model_forward():
    """stem_s2d=True keeps every downstream shape: logits and the
    non-stem parameter tree match the canonical model."""
    model = create_inception_v3(dtype=jnp.float32, stem_s2d=True)
    variables = init_inception(model, jax.random.PRNGKey(0), 299)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 299, 299, 3))
    logits, _ = model.apply(variables, x, train=True,
                            mutable=["batch_stats"])
    assert logits.shape == (2, 1000)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # stem conv is (2,2,12,32) instead of (3,3,3,32); everything else
    # is unchanged
    stem = variables["params"]["ConvBN_0"]["Conv_0"]["kernel"]
    assert stem.shape == (2, 2, 12, 32), stem.shape
