"""Inception V3: the lead model of the reference's benchmark table
(reference: docs/benchmarks.rst — Inception V3 ~90% scaling at 128
GPUs)."""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import create_inception_v3, init_inception


def test_inception_v3_param_count_and_forward():
    model = create_inception_v3(dtype=jnp.float32)
    variables = init_inception(model, jax.random.PRNGKey(0), 299)
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    # Canonical Inception V3 without the aux head, TF-slim BN
    # convention (no gamma): torchvision's 23,834,568 minus the
    # 17,216 BN scale params.
    assert n == 23_817_352, n

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 299, 299, 3))
    logits, updates = model.apply(variables, x, train=True,
                                  mutable=["batch_stats"])
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in updates


def test_inception_v3_train_step_reduces_loss():
    import optax
    model = create_inception_v3(num_classes=10, dtype=jnp.float32)
    variables = init_inception(model, jax.random.PRNGKey(0), 128)
    params, stats = variables["params"], variables["batch_stats"]
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128, 3))
    y = jnp.array([0, 1])

    def loss_fn(p, stats):
        logits, upd = model.apply(
            {"params": p, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, 10)
        loss = jnp.mean(-jnp.sum(
            onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, upd["batch_stats"]

    opt = optax.sgd(0.01)
    state = opt.init(params)
    step = jax.jit(lambda p, s, st: _step(p, s, st))

    def _step(p, s, st):
        (loss, s2), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, s)
        updates, st2 = opt.update(grads, st, p)
        return optax.apply_updates(p, updates), s2, st2, loss

    losses = []
    for _ in range(2):
        params, stats, state, loss = step(params, stats, state)
        losses.append(float(loss))
    # one step on the fixed batch reduces its loss (tiny-batch SGD
    # oscillates over longer horizons — not what this asserts)
    assert losses[1] < losses[0], losses
