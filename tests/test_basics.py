"""Lifecycle, topology, config, metadata tests
(reference analog: test/single/test_run.py basics + hvd API queries in
test/parallel/test_torch.py)."""

import os

import pytest


def test_init_rank_size(hvd_single):
    hvd = hvd_single
    assert hvd.is_initialized()
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def test_init_idempotent(hvd_single):
    hvd = hvd_single
    hvd.init()
    assert hvd.rank() == 0


def test_uninitialized_raises():
    import horovod_tpu as hvd
    hvd.shutdown()
    with pytest.raises(RuntimeError, match="init"):
        hvd.rank()


def test_shutdown_and_reinit():
    import horovod_tpu as hvd
    hvd.init()
    assert hvd.is_initialized()
    hvd.shutdown()
    assert not hvd.is_initialized()
    hvd.init()
    assert hvd.size() == 1
    hvd.shutdown()


def test_config_env_parsing():
    from horovod_tpu.common.config import Config
    cfg = Config(env={"HOROVOD_FUSION_THRESHOLD": "1048576",
                      "HOROVOD_CYCLE_TIME": "2.5",
                      "HOROVOD_AUTOTUNE": "true",
                      "HOROVOD_LOG_LEVEL": "debug"})
    assert cfg.fusion_threshold == 1048576
    assert cfg.cycle_time_ms == 2.5
    assert cfg.autotune is True
    assert cfg.log_level == "debug"
    # defaults
    assert cfg.cache_capacity == 1024
    assert cfg.stall_check_time == 60.0


def test_config_bad_value():
    from horovod_tpu.common.config import Config
    with pytest.raises(ValueError, match="HOROVOD_FUSION_THRESHOLD"):
        Config(env={"HOROVOD_FUSION_THRESHOLD": "lots"})


def test_config_overrides():
    from horovod_tpu.common.config import Config
    cfg = Config(overrides={"HOROVOD_CYCLE_TIME": 7.0})
    assert cfg.cycle_time_ms == 7.0


def test_describe_knobs_lists_everything():
    from horovod_tpu.common.config import KNOBS, describe_knobs
    text = describe_knobs()
    for k in KNOBS:
        assert k.env in text


def test_metadata_flags():
    import horovod_tpu as hvd
    # The north-star constraint: never NCCL/MPI/Gloo.
    assert not hvd.nccl_built()
    assert not hvd.mpi_built()
    assert not hvd.gloo_built()
    assert not hvd.cuda_built()
    assert hvd.xla_built()
    summary = hvd.check_build_summary()
    assert "XLA collectives" in summary
    assert "NCCL (never linked" in summary
    import importlib.util
    expect = ("[X]" if importlib.util.find_spec("torch") else "[ ]")
    assert f"{expect} torch frontend binding" in summary


def test_process_set_registration(hvd_single):
    import horovod_tpu as hvd
    ps = hvd.add_process_set([0])
    assert ps.process_set_id is not None
    assert ps.included()
    assert ps.rank() == 0
    # duplicate registration returns the same set
    ps2 = hvd.add_process_set([0])
    assert ps2.process_set_id == ps.process_set_id


def test_process_set_out_of_range(hvd_single):
    import horovod_tpu as hvd
    with pytest.raises(ValueError, match="out of range"):
        hvd.add_process_set([0, 5])


def test_capability_shims_match_reference_contract():
    """The reference's capability probes must exist and answer
    honestly: no NCCL/MPI/Gloo anywhere (the data plane is XLA over
    PJRT), XLA always built (reference: horovod/metadata and
    mpi_ops.py mpi_threads_supported)."""
    import horovod_tpu as hvd
    assert hvd.nccl_built() is False
    assert hvd.mpi_built() is False
    assert hvd.gloo_built() is False
    assert hvd.cuda_built() is False
    assert hvd.rocm_built() is False
    assert hvd.ddl_built() is False
    assert hvd.ccl_built() is False
    assert hvd.nccl_enabled() is False
    assert hvd.mpi_enabled() is False
    assert hvd.gloo_enabled() is False
    assert hvd.mpi_threads_supported() is False
    assert hvd.xla_built() is True
