"""Worker for the 2-rank PowerSGD crash/restore test: eager-plane
DistributedGradientTransformation with Compression.powersgd — the warm
Q factors and the error-feedback residual live INSIDE the optax state,
so the ordinary elastic `JaxState(params, opt_state)` commit carries
them with zero extra plumbing. Three phases via
COMPRESSION_WORKER_PHASE:

  ref — 6 uninterrupted steps, record {loss, residual_norm}
  a   — 3 steps, commit through JaxState's pickle snapshot, hard-exit
        mid-"step 4" (os._exit: no atexit, no shutdown — the crash)
  b   — restore the commit, run the remaining 3 steps, record the
        same probe; the test pins resumed == ref

Per-rank batches differ (the reduction is load-bearing), parameters
stay replicated, and every step's reduced gradient is identical across
ranks — so both ranks can restore the shared snapshot file directly
(same machine in this harness; the driver's sync() broadcast covers
the multi-host case)."""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.elastic.state import JaxState  # noqa: E402
from horovod_tpu.ops.compression import Compression  # noqa: E402
from horovod_tpu.optim.distributed_optimizer import (  # noqa: E402
    DistributedGradientTransformation)


def loss_fn(params, batch):
    h = jnp.tanh(batch[:, None] * params["w1"][None, :])
    return jnp.mean((h @ params["w2"]) ** 2) + jnp.mean(
        params["b"] ** 2)


def init_params():
    # w2 (32x16 f32, 512 elements) is the powersgd-eligible leaf at
    # min_elements=256; w1/b bypass to the exact grouped path.
    return {"w1": jnp.arange(32.0) / 32.0,
            "w2": jnp.ones((32, 16)) * 0.1
            + jnp.arange(32.0 * 16).reshape(32, 16) * 1e-3,
            "b": jnp.zeros(3)}


def main():
    phase = os.environ["COMPRESSION_WORKER_PHASE"]
    outdir = os.environ["COMPRESSION_WORKER_DIR"]
    snap = os.path.join(outdir, "snap.pkl")

    hvd.init()
    r = hvd.rank()
    assert hvd.size() == 2

    opt = DistributedGradientTransformation(
        optax.adam(0.05),
        compression=Compression.powersgd(rank=2, min_elements=256,
                                         warmup_steps=0))
    params = init_params()
    opt_state = opt.init(params)
    assert opt_state.q and opt_state.e, "powersgd leaf not eligible?"
    batch = jnp.arange(8.0) + 8.0 * r  # per-rank shard
    probe = jnp.arange(8.0) * 0.5     # fixed, rank-independent

    def step(params, opt_state):
        grads = jax.grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def run(params, opt_state, n):
        for _ in range(n):
            params, opt_state = step(params, opt_state)
        return params, opt_state

    state = JaxState(params=params, opt_state=opt_state,
                     snapshot_path=snap, snapshot_backend="pickle",
                     step=0)
    resumed = state.maybe_load_snapshot()

    if phase == "ref":
        assert not resumed
        params, opt_state = run(params, opt_state, 6)
    elif phase == "a":
        assert not resumed
        params, opt_state = run(params, opt_state, 3)
        state.params, state.opt_state, state.step = params, \
            opt_state, 3
        state.save()  # the commit (rank 0 writes the snapshot)
        hvd.barrier()  # both ranks see the durable commit
        print("COMPRESSION WORKER COMMITTED rank=%d step=3" % r,
              flush=True)
        sys.stdout.flush()
        os._exit(1)   # the crash: mid-"step 4", no shutdown
    elif phase == "b":
        assert resumed, "phase b found no snapshot to restore"
        assert int(state.step) == 3
        params, opt_state = state.params, state.opt_state
        # the residual survived the crash — it is gradient signal
        res0 = float(np.sqrt(sum(
            float((np.asarray(e, np.float64) ** 2).sum())
            for e in opt_state.e.values())))
        assert res0 > 0, "restored residual is zero"
        params, opt_state = run(params, opt_state, 3)
    else:
        raise SystemExit(f"unknown phase {phase!r}")

    res_norm = float(np.sqrt(sum(
        float((np.asarray(e, np.float64) ** 2).sum())
        for e in opt_state.e.values())))
    doc = {"loss": float(loss_fn(params, probe)),
           "residual_norm": res_norm,
           "powersgd_step": int(opt_state.step)}
    if r == 0:
        name = "ref.json" if phase == "ref" else "resumed.json"
        with open(os.path.join(outdir, name), "w") as f:
            json.dump(doc, f)
    hvd.barrier()
    hvd.shutdown()
    print(f"COMPRESSION WORKER OK rank={r} phase={phase} "
          f"loss={doc['loss']:.6f} residual={res_norm:.4f}",
          flush=True)


main()
