"""Fault-injection subsystem tests: spec grammar (including loud
rejection of malformed specs), per-seam deterministic schedules under
a fixed seed, the disarmed fast-path overhead guard, the wire seams +
BasicClient retry/backoff against a flaky BasicService, worker
heartbeats through the rendezvous, the discovery circuit breaker, and
the escalating host blacklist."""

import os
import time

import pytest

from horovod_tpu import faults
from horovod_tpu.metrics import REGISTRY


@pytest.fixture(autouse=True)
def disarm():
    """Every test starts and ends with the plan disarmed — the plan is
    module-global and must never leak into unrelated tests."""
    faults.configure(None)
    yield
    faults.configure(None)


class TestSpecGrammar:
    def test_parse_multi_rule_with_params(self):
        rules = faults.parse(
            "wire.send:drop:p=0.05;elastic.step:crash:at=40;"
            "discovery.poll:error", seed=3)
        assert [(r.point, r.action) for r in rules] == [
            ("wire.send", "drop"), ("elastic.step", "crash"),
            ("discovery.poll", "error")]
        assert rules[0].p == 0.05
        assert rules[1].at == 40
        assert rules[2].p == 1.0

    def test_empty_rules_and_whitespace_tolerated(self):
        rules = faults.parse(" wire.send : delay : ms=5 ; ;", seed=0)
        assert len(rules) == 1 and rules[0].ms == 5.0

    @pytest.mark.parametrize("bad", [
        "nosuch.point:drop",              # unknown point
        "wire.send:teleport",             # unknown action
        "wire.send",                      # missing action
        "wire.send:drop:p=0.5:extra",     # too many segments
        "wire.send:drop:p=oops",          # bad number
        "wire.send:drop:p=2.0",           # probability out of range
        "wire.send:drop:frobnicate=1",    # unknown param
        "wire.send:drop:p0.5",            # param without '='
        "dispatch.entry:drop",            # action unimplemented there
        "rendezvous.http:corrupt",        # action unimplemented there
    ])
    def test_malformed_specs_rejected(self, bad):
        with pytest.raises(ValueError):
            faults.parse(bad)

    def test_configure_arms_and_disarms(self):
        assert not faults.active()
        faults.configure("dispatch.entry:delay:ms=1", seed=1)
        assert faults.active()
        faults.configure(None)
        assert not faults.active()


class TestFiring:
    def test_at_fires_exactly_once_on_nth_hit(self):
        faults.configure("wire.send:drop:at=3", seed=0)
        got = [faults.fire("wire.send") for _ in range(6)]
        assert got == [None, None, "drop", None, None, None]

    def test_every_and_times(self):
        faults.configure("wire.send:drop:every=2,times=2", seed=0)
        got = [faults.fire("wire.send") for _ in range(8)]
        assert got == [None, "drop", None, "drop", None, None, None,
                       None]

    def test_probability_deterministic_under_seed(self):
        def schedule(seed):
            faults.configure("wire.send:drop:p=0.3", seed=seed)
            return [i for i in range(200)
                    if faults.fire("wire.send") == "drop"]

        a = schedule(7)
        b = schedule(7)
        c = schedule(8)
        assert a == b                      # same seed, same schedule
        assert a != c                      # different seed moves it
        assert 20 < len(a) < 100           # p=0.3 is actually applied

    def test_streams_independent_across_points(self):
        """One point's traffic must not perturb another's schedule —
        each rule draws from its own (seed, point, action) stream."""
        faults.configure("wire.recv:drop:p=0.3;"
                         "wire.send:drop:p=0.3", seed=5)
        a = [i for i in range(100)
             if faults.fire("wire.send") == "drop"]
        # Re-arm; interleave heavy wire.recv traffic this time.
        faults.configure("wire.recv:drop:p=0.3;"
                         "wire.send:drop:p=0.3", seed=5)
        b = []
        for i in range(100):
            try:
                faults.fire("wire.recv")
            except Exception:
                pass
            if faults.fire("wire.send") == "drop":
                b.append(i)
        assert a == b

    def test_error_raises_seam_exception(self):
        faults.configure("discovery.poll:error:at=1", seed=0)
        with pytest.raises(RuntimeError, match="injected fault"):
            faults.fire("discovery.poll", exc=RuntimeError)

    def test_error_default_exception(self):
        faults.configure("elastic.step:error:at=1", seed=0)
        with pytest.raises(faults.FaultInjected):
            faults.fire("elastic.step")

    def test_delay_sleeps(self):
        faults.configure("dispatch.entry:delay:ms=50,at=1", seed=0)
        t0 = time.perf_counter()
        assert faults.fire("dispatch.entry") == "delay"
        assert time.perf_counter() - t0 >= 0.04

    def test_rank_scoping(self, monkeypatch):
        faults.configure("wire.send:drop:rank=1", seed=0)
        monkeypatch.setenv("HOROVOD_RANK", "0")
        assert faults.fire("wire.send") is None
        monkeypatch.setenv("HOROVOD_RANK", "1")
        assert faults.fire("wire.send") == "drop"

    def test_once_latch_survives_rearm(self, tmp_path):
        """The filesystem latch is what keeps an exactly-once crash
        exactly-once across a gang restart (the respawned process
        re-arms the schedule from env with fresh hit counters)."""
        latch = str(tmp_path / "latch")
        spec = f"wire.send:drop:at=1,once={latch}"
        faults.configure(spec, seed=0)
        assert faults.fire("wire.send") == "drop"
        faults.configure(spec, seed=0)     # "restarted process"
        assert faults.fire("wire.send") is None

    def test_fired_metric_counts_by_point_and_action(self):
        c = REGISTRY.get("hvd_faults_fired_total")
        key = ("wire.send", "drop")
        before = c.labels(point=key[0], action=key[1]).value()
        faults.configure("wire.send:drop:times=3", seed=0)
        for _ in range(5):
            faults.fire("wire.send")
        after = c.labels(point=key[0], action=key[1]).value()
        assert after - before == 3

    def test_commit_boundary_raises_horovod_internal_error(self):
        """The elastic.step seam's "error" action surfaces as
        HorovodInternalError from State.commit — the exception class
        the elastic run() wrapper's restore + re-init path catches."""
        from horovod_tpu.elastic.state import (HorovodInternalError,
                                               ObjectState)
        st = ObjectState(bcast_object=lambda obj, root_rank=0: obj,
                         step=0)
        faults.configure("elastic.step:error:at=1", seed=0)
        with pytest.raises(HorovodInternalError):
            st.commit()
        st.commit()  # at=1 fired; later commits run clean


def test_disarmed_fast_path_overhead():
    """Tier-1 perf guard (same shape as the metrics registry's
    fast-path guard): with HOROVOD_FAULTS unset, every injection
    point is one module-attribute load + compare. The bound is
    generous for a loaded CI host; it catches a pathological
    regression (parsing/locking on the hot path), not micro-drift."""
    assert not faults.active()
    n = 50000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.fire("dispatch.entry")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"{per_call * 1e6:.2f} us/call"


class TestWireSeamsAndClientRetry:
    def _service(self, secret="s3cr3t"):
        from horovod_tpu.runner.service import BasicClient, BasicService
        svc = BasicService("flaky-test", secret, 0)
        svc.handle("ping", lambda req, peer: {"pong": req.get("n")})
        cli = BasicClient("127.0.0.1", svc.port, secret, timeout=5.0)
        return svc, cli

    def test_retry_recovers_from_transient_wire_errors(self):
        svc, cli = self._service()
        try:
            # The client's FIRST send raises an injected OSError at
            # the wire.send seam (at=1 pins it to one deterministic
            # failure — the server's own reply sends share the plan's
            # hit counter in-process, so probabilistic specs here
            # would race); the retry goes through.
            faults.configure("wire.send:error:at=1", seed=0)
            retries = REGISTRY.get("hvd_control_retries_total")
            r0 = retries.labels(op="request").value()
            reply = cli.request({"type": "ping", "n": 7}, retries=3,
                                backoff=0.01)
            assert reply == {"pong": 7}
            assert retries.labels(op="request").value() - r0 == 1
        finally:
            svc.close()

    def test_no_retry_budget_propagates(self):
        svc, cli = self._service()
        try:
            faults.configure("wire.send:error:at=1", seed=0)
            with pytest.raises(OSError):
                cli.request({"type": "ping", "n": 1})
        finally:
            svc.close()

    def test_denied_is_never_retried(self):
        """An auth denial must fail fast even with a retry budget — a
        bad secret does not heal, and N pointless retries would mask
        the misconfiguration. A raw one-shot server always answers a
        properly-signed denial, so the client's denied fast-path is
        exercised in isolation."""
        import socket
        import threading
        from horovod_tpu.runner.service import (BasicClient, WireError,
                                                send_frame)
        secret = "shared"
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)

        def deny_loop():
            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                with conn:
                    try:
                        conn.settimeout(2.0)
                        conn.recv(1 << 16)   # drain the request first
                        send_frame(conn, secret, {"error": "denied"})
                    except OSError:
                        pass

        t = threading.Thread(target=deny_loop, daemon=True)
        t.start()
        cli = BasicClient("127.0.0.1", srv.getsockname()[1], secret,
                          timeout=5.0)
        try:
            t0 = time.perf_counter()
            with pytest.raises(WireError, match="denied"):
                cli.request({"type": "ping"}, retries=5, backoff=1.0)
            # 5 retries at backoff=1.0 would take >= 2.5 s even with
            # min jitter; failing fast proves no retry happened.
            assert time.perf_counter() - t0 < 2.0
        finally:
            srv.close()

    def test_corrupt_frame_rejected_by_receiver(self):
        from horovod_tpu.runner.service import BasicClient
        svc, cli = self._service()
        try:
            faults.configure("wire.send:corrupt:at=1", seed=0)
            # The corrupted request fails the server's HMAC check ->
            # denied; a clean retry from scratch succeeds.
            from horovod_tpu.runner.service import WireError
            with pytest.raises(WireError):
                cli.request({"type": "ping", "n": 1})
            assert cli.request({"type": "ping", "n": 2}) == {"pong": 2}
        finally:
            svc.close()


class TestHeartbeats:
    def test_worker_heartbeat_lands_in_rendezvous(self, monkeypatch):
        from horovod_tpu.elastic import worker
        from horovod_tpu.runner import secret as _secret
        from horovod_tpu.runner.elastic import RendezvousServer
        secret = _secret.make_secret()
        rs = RendezvousServer(secret=secret)
        try:
            monkeypatch.setenv(_secret.ENV_VAR, secret)
            monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR",
                               f"localhost:{rs.port}")
            monkeypatch.setenv("HOROVOD_HOSTNAME", "hostA")
            monkeypatch.setenv("HOROVOD_LOCAL_RANK", "2")
            t0 = time.time()
            assert worker._heartbeat_once()
            beats = rs.heartbeats()
            assert ("hostA", 2) in beats
            assert beats[("hostA", 2)] >= t0 - 1
            rs.clear_heartbeat(("hostA", 2))
            assert ("hostA", 2) not in rs.heartbeats()
        finally:
            rs.stop()

    def test_unsigned_heartbeat_rejected(self, monkeypatch):
        import urllib.error
        import urllib.request
        from horovod_tpu.runner import secret as _secret
        from horovod_tpu.runner.elastic import RendezvousServer
        rs = RendezvousServer(secret=_secret.make_secret())
        try:
            req = urllib.request.Request(
                f"http://localhost:{rs.port}/heartbeat/hostA/0",
                data=b"{}", method="PUT")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 403
            assert rs.heartbeats() == {}
        finally:
            rs.stop()

    def test_interval_auto_derives_from_timeout(self, monkeypatch):
        from horovod_tpu.elastic import worker
        monkeypatch.setenv("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", "9")
        monkeypatch.delenv("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL",
                           raising=False)
        assert worker.heartbeat_interval() == 3.0
        monkeypatch.setenv("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL", "1.5")
        assert worker.heartbeat_interval() == 1.5

    def test_start_heartbeat_noop_when_disabled(self, monkeypatch):
        from horovod_tpu.elastic import worker
        monkeypatch.delenv("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT",
                           raising=False)
        assert not worker.start_heartbeat()


class TestResilientDiscovery:
    class _Flaky:
        def __init__(self, hosts):
            self.hosts = hosts
            self.fail = False
            self.calls = 0

        def find_available_hosts_and_slots(self):
            self.calls += 1
            if self.fail:
                raise RuntimeError("discovery down")
            return list(self.hosts)

    def test_serves_last_known_good_inside_window(self):
        from horovod_tpu.runner.elastic.discovery import (
            ResilientDiscovery)
        from horovod_tpu.runner.hosts import HostSlots
        inner = self._Flaky([HostSlots("h1", 2)])
        d = ResilientDiscovery(inner, staleness_window=60.0)
        assert [h.host for h in
                d.find_available_hosts_and_slots()] == ["h1"]
        inner.fail = True
        got = d.find_available_hosts_and_slots()   # served from cache
        assert [h.host for h in got] == ["h1"]
        assert d.consecutive_failures == 1
        inner.fail = False
        d.find_available_hosts_and_slots()
        assert d.consecutive_failures == 0

    def test_propagates_past_window_and_with_no_cache(self):
        from horovod_tpu.runner.elastic.discovery import (
            ResilientDiscovery)
        from horovod_tpu.runner.hosts import HostSlots
        inner = self._Flaky([HostSlots("h1", 2)])
        inner.fail = True
        d = ResilientDiscovery(inner, staleness_window=60.0)
        with pytest.raises(RuntimeError):      # nothing cached yet
            d.find_available_hosts_and_slots()
        inner.fail = False
        d.find_available_hosts_and_slots()
        d._last_good_time -= 120.0             # age the cache out
        inner.fail = True
        with pytest.raises(RuntimeError):
            d.find_available_hosts_and_slots()

    def test_injected_discovery_fault_absorbed_by_breaker(self):
        from horovod_tpu.runner.elastic.discovery import (
            FixedHosts, ResilientDiscovery)
        d = ResilientDiscovery(FixedHosts("", 2), staleness_window=60)
        d.find_available_hosts_and_slots()     # primes the cache
        # Hit counters start at the configure() below, so at=1 is the
        # next poll — the one served from the breaker's cache.
        faults.configure("discovery.poll:error:at=1", seed=0)
        got = d.find_available_hosts_and_slots()
        assert [h.slots for h in got] == [2]
        assert d.consecutive_failures == 1


class TestEscalatingBlacklist:
    def test_window_doubles_per_failure_and_caps(self):
        from horovod_tpu.runner.elastic import ElasticDriver, FixedHosts
        drv = ElasticDriver(["true"], FixedHosts("", 2),
                            env={"HOROVOD_ELASTIC_BLACKLIST_WINDOW":
                                 "60",
                                 "HOROVOD_ELASTIC_BLACKLIST_WINDOW_MAX":
                                 "300"})
        try:
            assert drv._blacklist_window_for("h") == 60.0
            for n, want in [(1, 60.0), (2, 120.0), (3, 240.0),
                            (4, 300.0), (9, 300.0)]:
                drv._host_failures["h"] = n
                assert drv._blacklist_window_for("h") == want
        finally:
            drv.rendezvous.stop()

    def test_blacklist_gauge_tracks_active_windows(self):
        from horovod_tpu.runner.elastic import ElasticDriver, FixedHosts
        g = REGISTRY.get("hvd_elastic_blacklisted_hosts")
        drv = ElasticDriver(["true"], FixedHosts("", 2))
        try:
            drv.blacklist = {"h1": time.time() + 60,
                             "h2": time.time() - 1}    # expired
            drv._discover()
            assert g.value() == 1
            drv.blacklist = {}
            drv._discover()
            assert g.value() == 0
        finally:
            drv.rendezvous.stop()
