"""Wire-parser fuzzing under ASan+UBSan (SURVEY.md §5.2 race/sanitizer
stance: the reference relies on FlatBuffers verification; this build's
hand-rolled format gets a hand-rolled fuzzer). Gated on the C++
toolchain like the TSAN stress."""

import os
import shutil
import subprocess

import pytest

CCDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core", "cc")


def _asan_available() -> bool:
    """Probe-compile a trivial -fsanitize=address program: only a
    missing libasan may skip the fuzz test — a compile-broken harness
    must FAIL, not silently vanish from CI."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "p.cc")
        with open(src, "w") as f:
            f.write("int main(){return 0;}\n")
        r = subprocess.run(
            ["g++", "-fsanitize=address", src, "-o",
             os.path.join(d, "p")],
            capture_output=True, timeout=120)
        return r.returncode == 0


@pytest.mark.integration
def test_wire_parsers_survive_fuzzing():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    if not _asan_available():
        pytest.skip("libasan unavailable")
    build = subprocess.run(["make", "-C", CCDIR, "fuzz_wire"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    r = subprocess.run([os.path.join(CCDIR, "fuzz_wire"), "30000"],
                       capture_output=True, text=True, timeout=300)
    assert "AddressSanitizer" not in r.stderr, r.stderr[-3000:]
    assert "runtime error" not in r.stderr, r.stderr[-3000:]
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    assert "FUZZ OK" in r.stdout, r.stdout
