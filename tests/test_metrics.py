"""Metrics subsystem tests: registry semantics, Prometheus text
exposition, live scrape endpoint, stall gauges, instrumentation seams,
and the timeline durability/error-marker fixes that rode along
(reference gap being closed: the reference's timeline.cc /
stall_inspector.cc findings die in log lines — nothing scrapeable)."""

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.metrics import (BYTES_BUCKETS, LATENCY_BUCKETS,
                                 Counter, Gauge, Histogram,
                                 MetricsRegistry, MetricsServer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One metric sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? "
    r"(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]Inf|NaN)$")


def assert_prometheus_text(text: str) -> None:
    """Every non-comment, non-blank line must be a valid sample."""
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


class TestRegistry:
    def test_concurrent_counter(self):
        """8 threads x 2000 increments land exactly — the unlocked
        += data race the engine's _bytes_processed had would lose
        updates here."""
        reg = MetricsRegistry()
        c = reg.counter("t_total", "t")

        def worker():
            for _ in range(2000):
                c.inc(3)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8 * 2000 * 3

    def test_counter_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "t")
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("t_gauge", "t")
        g.set(5)
        g.inc(2)
        g.dec(3)
        assert g.value() == 4

    def test_histogram_bucketing(self):
        """Log-scale buckets with Prometheus le semantics (v <= bound
        counts, including exact boundary hits) and a cumulative view."""
        reg = MetricsRegistry()
        h = reg.histogram("t_seconds", "t", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.01, 0.05, 0.5, 2.0):
            h.observe(v)
        val = h.value()
        assert val["count"] == 5
        assert abs(val["sum"] - 2.565) < 1e-9
        cum = dict(val["buckets"])
        assert cum[0.01] == 2          # 0.005 and the boundary 0.01
        assert cum[0.1] == 3
        assert cum[1.0] == 4
        assert cum[float("inf")] == 5

    def test_labels_required_and_checked(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "t", ("kind",))
        with pytest.raises(ValueError, match="labels"):
            c.inc()  # labeled metric needs .labels(...)
        with pytest.raises(ValueError, match="labels"):
            c.labels(wrong="x")
        c.labels(kind="a").inc(2)
        assert c.labels(kind="a").value() == 2
        assert c.labels(kind="b").value() == 0

    def test_idempotent_registration(self):
        reg = MetricsRegistry()
        a = reg.counter("t_total", "t", ("k",))
        assert reg.counter("t_total", "t", ("k",)) is a
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("t_total", "t", ("k",))
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("t_total", "t", ("other",))

    def test_label_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "t", ("name",))
        c.labels(name='we"ird\\path\nline').inc()
        text = reg.generate_text()
        assert r'name="we\"ird\\path\nline"' in text
        assert_prometheus_text(text)

    def test_prometheus_golden(self):
        """Exact text-exposition golden: format drift breaks real
        scrapers, so pin it byte for byte."""
        reg = MetricsRegistry()
        c = reg.counter("test_total", "A counter.", ("kind",))
        c.labels(kind="a").inc(3)
        g = reg.gauge("test_gauge", "A gauge.")
        g.set(2.5)
        h = reg.histogram("test_seconds", "A histogram.",
                          buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        expected = (
            '# HELP test_total A counter.\n'
            '# TYPE test_total counter\n'
            'test_total{kind="a"} 3\n'
            '# HELP test_gauge A gauge.\n'
            '# TYPE test_gauge gauge\n'
            'test_gauge 2.5\n'
            '# HELP test_seconds A histogram.\n'
            '# TYPE test_seconds histogram\n'
            'test_seconds_bucket{le="0.1"} 1\n'
            'test_seconds_bucket{le="1"} 1\n'
            'test_seconds_bucket{le="+Inf"} 2\n'
            'test_seconds_sum 5.05\n'
            'test_seconds_count 2\n')
        assert reg.generate_text() == expected

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "c", ("k",)).labels(k="x").inc(7)
        reg.gauge("g", "g").set(1.5)
        snap = reg.snapshot()
        assert snap["c_total"][("x",)] == 7
        assert snap["g"][()] == 1.5


def test_registry_fast_path_overhead():
    """Tier-1 perf guard: with no scrape server running, the
    registry-only fast path (one dict access + one lock'd add per
    record) must stay far below per-op dispatch cost. The bound is
    generous (100 µs/record on a loaded CI host vs sub-µs typical) —
    it catches pathological regressions (I/O, rendering, or lock
    convoys on the hot path), not micro-drift."""
    reg = MetricsRegistry()
    c = reg.counter("hot_total", "hot", ("pset",)).labels(pset="0")
    h = reg.histogram("hot_seconds", "hot", buckets=LATENCY_BUCKETS)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc(4096)
        h.observe(1e-4)
    per_record = (time.perf_counter() - t0) / (2 * n)
    assert per_record < 100e-6, f"{per_record * 1e6:.1f} us/record"


class TestScrapeServer:
    def test_live_scrape_and_404(self):
        reg = MetricsRegistry()
        reg.counter("up_total", "u").inc(2)
        srv = MetricsServer(0, reg)
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                text = resp.read().decode()
            assert "up_total 2" in text
            assert_prometheus_text(text)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=5)
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_init_knob_serves_and_shutdown_stops(self):
        """HOROVOD_METRICS_PORT through the full hvd lifecycle."""
        import horovod_tpu as hvd
        from horovod_tpu.common.basics import state
        port = _free_port_base(1)
        hvd.init(config_overrides={"HOROVOD_METRICS_PORT": port})
        try:
            assert state().metrics_server is not None
            assert state().metrics_server.port == port
            hvd.allreduce(jnp.ones(16), name="scrape0")
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=5).read().decode()
            assert "hvd_allreduce_bytes_total" in text
            assert "hvd_dispatch_latency_seconds_bucket" in text
            assert_prometheus_text(text)
        finally:
            hvd.shutdown()
        with pytest.raises(OSError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=2)


def test_stall_gauge_rises_and_clears():
    """Forced stall: a pending collective older than
    HOROVOD_STALL_CHECK_TIME_SECONDS must raise hvd_stalled_tensors
    (and a nonzero max age), and the gauges must clear once the
    pending drains — the alertable form of the stall inspector's
    log-only warning."""
    import horovod_tpu as hvd
    from horovod_tpu.common.basics import state
    from horovod_tpu.ops.compression import NoneCompressor
    from horovod_tpu.ops.controller import _PendingAllreduce
    hvd.init(config_overrides={
        "HOROVOD_CONTROLLER": "python",
        "HOROVOD_STALL_CHECK_TIME_SECONDS": 0.05})
    try:
        st = state()
        ctl = st.engine.controller
        pset = st.process_set_table.global_set
        h = st.engine.new_handle("stuck")
        # A pending entry the core never agrees on (submitted directly
        # into the registry, bypassing core.submit) — what a missing
        # peer looks like from this rank.
        with ctl._mu:
            ctl._pending["stuck"] = _PendingAllreduce(
                [jnp.ones(2)], NoneCompressor, pset, 0, 1.0, 1.0, h,
                False)
        deadline = time.time() + 10
        while time.time() < deadline:
            if hvd.metrics()["hvd_stalled_tensors"][()] >= 1:
                break
            time.sleep(0.02)
        snap = hvd.metrics()
        assert snap["hvd_stalled_tensors"][()] >= 1
        assert snap["hvd_stall_max_age_seconds"][()] >= 0.05
        with ctl._mu:
            ctl._pending.pop("stuck")
        h.set_error(RuntimeError("test cleanup"))
        deadline = time.time() + 10
        while time.time() < deadline:
            if hvd.metrics()["hvd_stalled_tensors"][()] == 0:
                break
            time.sleep(0.02)
        assert hvd.metrics()["hvd_stalled_tensors"][()] == 0
        assert hvd.metrics()["hvd_stall_max_age_seconds"][()] == 0
    finally:
        hvd.shutdown()


class TestInstrumentationSeams:
    def test_engine_bytes_and_latency(self, hvd_single):
        """Inline-path ops land in the engine counters and the
        dispatch-latency histogram; hvd_allreduce_bytes_total tracks
        raw payload bytes by process set."""
        before = hvd_single.metrics()

        def val(snap, name, key=()):
            return snap.get(name, {}).get(key, 0)

        hvd_single.allreduce(jnp.ones(1024, jnp.float32), name="im0")
        after = hvd_single.metrics()
        assert (val(after, "hvd_engine_bytes_total")
                - val(before, "hvd_engine_bytes_total")) == 4096
        assert (val(after, "hvd_engine_ops_total")
                - val(before, "hvd_engine_ops_total")) == 1
        assert (val(after, "hvd_allreduce_bytes_total", ("0",))
                - val(before, "hvd_allreduce_bytes_total",
                      ("0",))) == 4096
        dl_b = before.get("hvd_dispatch_latency_seconds",
                          {}).get((), {"count": 0})["count"]
        dl_a = after["hvd_dispatch_latency_seconds"][()]["count"]
        assert dl_a - dl_b >= 1

    def test_controller_fusion_and_program_cache_metrics(self):
        """The negotiated path scores batches/entries, the fusion
        histograms, negotiation latency, and the composition
        (compiled-program) cache hit/miss pair."""
        import horovod_tpu as hvd
        hvd.init(config_overrides={"HOROVOD_CONTROLLER": "python"})
        try:
            before = hvd.metrics()

            def val(snap, name, key=()):
                return snap.get(name, {}).get(key, 0)

            for i in range(3):
                hvd.allreduce(jnp.ones(64), name=f"fc{i}")
            after = hvd.metrics()
            assert (val(after, "hvd_fused_batches_total", ("ar",))
                    - val(before, "hvd_fused_batches_total",
                          ("ar",))) >= 3
            hits = (val(after, "hvd_fused_program_cache_hits_total")
                    - val(before,
                          "hvd_fused_program_cache_hits_total"))
            misses = (val(after,
                          "hvd_fused_program_cache_misses_total")
                      - val(before,
                            "hvd_fused_program_cache_misses_total"))
            batches = (val(after, "hvd_fused_batches_total", ("ar",))
                       - val(before, "hvd_fused_batches_total",
                             ("ar",)))
            # same composition 3x: >= 1 miss (first), the rest hits;
            # every allreduce batch scores exactly one of the two
            assert misses >= 1
            assert hits + misses == batches, (hits, misses, batches)
            neg = after["hvd_negotiation_latency_seconds"][()]
            neg0 = before.get("hvd_negotiation_latency_seconds",
                              {}).get((), {"count": 0})
            assert neg["count"] - neg0["count"] >= 3
            fb = after["hvd_fusion_batch_bytes"][()]
            assert fb["count"] >= 3
        finally:
            hvd.shutdown()

    def test_autotune_knob_gauges(self):
        from horovod_tpu.autotune import Autotuner
        from horovod_tpu.common.config import Config
        from horovod_tpu.metrics import REGISTRY
        t = Autotuner(Config({"HOROVOD_AUTOTUNE": True,
                              "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": 0,
                              "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": 1},
                             env={}))
        g = REGISTRY.get("hvd_autotune_fusion_threshold_bytes")
        assert g.value() == t.fusion_threshold
        t.record(1000, 0.001)  # one sample -> knob step + republish
        assert g.value() == t.fusion_threshold
        assert REGISTRY.get(
            "hvd_autotune_cycle_time_ms").value() == t.cycle_time_ms

    def test_elastic_state_counters(self):
        from horovod_tpu.elastic.state import ObjectState
        from horovod_tpu.metrics import REGISTRY
        commits = REGISTRY.get("hvd_elastic_commits_total")
        restores = REGISTRY.get("hvd_elastic_restores_total")
        c0, r0 = commits.value(), restores.value()
        st = ObjectState(bcast_object=lambda obj, root_rank=0: obj,
                         epoch=1)
        st.commit()
        st.epoch = 99
        st.restore()
        assert commits.value() - c0 == 1
        assert restores.value() - r0 == 1
        assert st.epoch == 1


class TestLogRank0Only:
    def teardown_method(self, _):
        from horovod_tpu.common import logging as hlog
        hlog.set_rank0_only(False)

    def collect(self, rank, emit):
        import logging
        from horovod_tpu.common import logging as hlog
        records = []

        class Grab(logging.Handler):
            def emit(self, record):
                records.append(record)

        old_rank = hlog._rank_filter.rank
        old_level = hlog.logger.level
        g = Grab(level=logging.DEBUG)
        g.addFilter(hlog._rank_filter)
        hlog.logger.addHandler(g)
        hlog.logger.setLevel(logging.DEBUG)
        hlog.set_rank(rank)
        try:
            emit()
        finally:
            hlog.logger.removeHandler(g)
            hlog.logger.setLevel(old_level)
            hlog._rank_filter.rank = old_rank
        return [r.getMessage() for r in records]

    def test_nonzero_rank_suppresses_info_keeps_warning(self):
        from horovod_tpu.common import logging as hlog
        hlog.set_rank0_only(True)

        def emit():
            hlog.info("info-msg")
            hlog.debug("debug-msg")
            hlog.warning("warn-msg")

        msgs = self.collect(3, emit)
        assert "info-msg" not in msgs and "debug-msg" not in msgs
        assert "warn-msg" in msgs
        # rank 0 keeps everything
        msgs0 = self.collect(0, emit)
        assert "info-msg" in msgs0 and "warn-msg" in msgs0


class TestTimelineFixes:
    def test_done_error_emits_marker_inside_span(self, tmp_path):
        """Timeline.done(name, error=True) must emit the ERROR instant
        BEFORE closing the DISPATCH span (the error flag was silently
        ignored), and the trace must stay balanced."""
        from horovod_tpu.timeline import Timeline
        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        tl.enqueue("t")
        tl.dispatched("t")
        tl.done("t", error=True)
        tl.close()
        events = json.load(open(path))
        errors = [e for e in events if e["name"] == "ERROR"]
        assert len(errors) == 1 and errors[0]["ph"] == "i"
        d_end = [e for e in events
                 if e["name"] == "DISPATCH" and e["ph"] == "E"][0]
        assert errors[0]["ts"] <= d_end["ts"]
        assert errors[0]["tid"] == d_end["tid"]
        opens = {}
        for e in events:
            key = (e.get("tid"), e["name"])
            if e["ph"] == "B":
                opens[key] = opens.get(key, 0) + 1
            elif e["ph"] == "E":
                opens[key] = opens.get(key, 0) - 1
        assert all(v == 0 for v in opens.values()), opens

    def test_writer_flushes_without_close(self, tmp_path):
        """Durability: events must reach the file shortly after the
        writer drains the queue, WITHOUT close() — a SIGKILLed rank
        keeps its trace up to the last quiet moment (the writer never
        flushed before, so a killed rank lost everything)."""
        from horovod_tpu.timeline import Timeline
        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        tl.enqueue("persist_me")
        tl.dispatched("persist_me")
        deadline = time.time() + 5
        content = ""
        while time.time() < deadline:
            content = open(path).read()
            if "persist_me" in content and "DISPATCH" in content:
                break
            time.sleep(0.02)
        assert "persist_me" in content, "no flush before close()"
        assert "DISPATCH" in content
        tl.close()


def _free_port_base(n: int = 2) -> int:
    """A base port with n consecutive free ports (rank i binds
    base + local_rank i)."""
    import random
    for _ in range(64):
        base = random.randint(20000, 45000)
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port pair found")


@pytest.mark.integration
def test_metrics_scrape_two_ranks():
    """Acceptance path: a live /metrics scrape during a 2-rank
    multiprocess run returns valid Prometheus text with the allreduce
    byte counter, the dispatch-latency histogram, and the stall gauge
    — and hvd.metrics() agrees with the scraped numbers in-process
    (asserted inside the worker)."""
    base = _free_port_base(2)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HOROVOD_METRICS_PORT"] = str(base)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, os.path.join("tests", "mp_worker_metrics.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip("this jaxlib's CPU backend cannot run cross-"
                    "process collectives (affects every multiprocess "
                    "integration test)")
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("METRICS ALL OK") == 2
