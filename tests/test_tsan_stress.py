"""Race detection for the native control plane (SURVEY.md §5.2: the
reference ships no TSAN harness — this build adds one). Builds the
ThreadSanitizer-instrumented controller stress binary and runs it:
zero TSAN reports AND identical agreed order on both ranks required.
"""

import os
import shutil
import subprocess

import pytest

CCDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core", "cc")


@pytest.mark.integration
def test_controller_stress_under_tsan():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, "stress_tsan"],
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0:
        # e.g. libtsan not installed on this host
        pytest.skip(f"tsan build unavailable: {build.stderr[-500:]}")
    r = subprocess.run([os.path.join(CCDIR, "stress_tsan")],
                       capture_output=True, text=True, timeout=180)
    assert "ThreadSanitizer" not in r.stderr, r.stderr[-3000:]
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    assert "ORDER OK" in r.stdout, r.stdout
