"""Distributed-tracing subsystem tests (tracing.py + the timeline.py
surgery): flight-recorder ring bounds, the NTP-style clock-offset
estimator on synthetic skew, merge byte-stability + straggler
attribution on synthetic per-rank files, SIGUSR2/postmortem dumps,
the always-on hot-path overhead guard (same style as faults.py's
disarmed guard), and a 2-rank integration run behind the multiproc
capability probe."""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

from horovod_tpu import tracing
from horovod_tpu.common import config as hconfig
from horovod_tpu.timeline import Timeline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def default_ring():
    """Restore the environment-configured ring after tests that
    resize/disable it."""
    yield
    tracing.configure_ring(hconfig.env_value("HOROVOD_TRACE_RING_SIZE"))


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self, default_ring):
        tracing.configure_ring(8)
        for i in range(50):
            tracing.record("dispatch", f"t{i}", i)
        evs = tracing.ring_events()
        assert len(evs) == 8
        # oldest events fell off; the tail is the newest
        assert [e[2] for e in evs] == [f"t{i}" for i in range(42, 50)]
        assert evs[-1][3] == 49
        assert tracing.ring_events(limit=3) == evs[-3:]

    def test_ring_disabled_is_noop(self, default_ring):
        tracing.configure_ring(0)
        tracing.record("dispatch", "nope")
        assert tracing.ring_events() == []

    def test_hot_path_overhead(self, default_ring):
        """Tier-1 perf guard (same shape as faults.py's disarmed
        guard): the always-on ring append — the ONLY per-span cost
        with HOROVOD_TIMELINE unset — and the fully-disabled path
        both stay bounded. Generous bound for a loaded CI host."""
        n = 50000
        tracing.configure_ring(4096)           # the always-on default
        t0 = time.perf_counter()
        for _ in range(n):
            tracing.record("dispatch", "guard")
        per_call_on = (time.perf_counter() - t0) / n
        tracing.configure_ring(0)              # ring disabled
        t0 = time.perf_counter()
        for _ in range(n):
            tracing.record("dispatch", "guard")
        per_call_off = (time.perf_counter() - t0) / n
        assert per_call_on < 20e-6, f"{per_call_on * 1e6:.2f} us/call"
        assert per_call_off < 20e-6, f"{per_call_off * 1e6:.2f} us/call"


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_seq_reservation_and_step(self):
        tracing.reset_context()
        assert tracing.next_seq(3) == 0
        assert tracing.next_seq() == 3
        tracing.set_step(7)
        assert tracing.current_step() == 7
        assert tracing.advance_step() == 8
        tracing.reset_context()
        assert tracing.next_seq() == 0


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

class TestClockOffset:
    def test_estimator_recovers_synthetic_skew(self):
        """A fake rank-0 clock 7.5 s ahead, probed through jittery
        round trips: the min-RTT midpoint estimate must recover the
        skew within its own RTT bound (the NTP guarantee: the server
        read falls inside [send, recv], so |error| <= rtt/2)."""
        skew_ns = 7_500_000_000
        rng = random.Random(3)

        def probe():
            time.sleep(rng.random() * 0.002)
            return time.monotonic_ns() + skew_ns

        off, rtt = tracing.estimate_offset(probe, probes=8)
        assert abs(off - skew_ns) <= rtt
        assert abs(off - skew_ns) < 5_000_000  # < 5 ms in practice

    def test_estimator_zero_skew(self):
        off, rtt = tracing.estimate_offset(time.monotonic_ns,
                                           probes=4)
        assert abs(off) <= max(rtt, 1_000_000)

    def test_time_service_roundtrip(self):
        """The real wire: a TimeService probed through the
        authenticated BasicClient; same process => same clock, so the
        estimate must be within the RTT bound of zero."""
        from horovod_tpu.runner.service import BasicClient
        svc = tracing.TimeService("s3cr3t-trace")
        try:
            cli = BasicClient("127.0.0.1", svc.port, "s3cr3t-trace",
                              timeout=5.0)

            def probe():
                return int(cli.request({"type": "time"})["mono_ns"])

            off, rtt = tracing.estimate_offset(probe, probes=4)
            assert abs(off) <= rtt
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# timeline anchor + per-rank paths
# ---------------------------------------------------------------------------

class TestTimelineAnchor:
    def test_meta_record_and_monotonic_anchor(self, tmp_path):
        path = str(tmp_path / "tl.json")
        tl = Timeline(path, rank=3)
        tl.enqueue("t1")
        tl.dispatched("t1")
        tl.done("t1")
        tl.clock_sync(-123456, 789)
        tl.close()
        events = json.load(open(path))
        meta = [e for e in events if e["name"] == "hvd_trace_meta"]
        assert len(meta) == 1
        args = meta[0]["args"]
        assert args["rank"] == 3
        assert args["anchor_mono_ns"] > 0
        assert args["anchor_unix_ns"] > 0
        sync = [e for e in events if e["name"] == "CLOCK_SYNC"]
        assert sync and sync[0]["args"]["offset_ns"] == -123456
        # span timestamps are monotonic-since-anchor, small positive us
        spans = [e for e in events if "ts" in e]
        assert all(0 <= e["ts"] < 60e6 for e in spans)

    def test_rank_path(self):
        assert Timeline.rank_path("tl.json", 0) == "tl.json"
        assert Timeline.rank_path("tl.json", 2) == "tl.rank2.json"
        assert Timeline.rank_path("/a/b/trace", 1) == "/a/b/trace.rank1.json"

    def test_negotiate_end_carries_trace_context(self, tmp_path):
        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        tl.negotiate_start("g0")
        tl.negotiate_end("g0", negotiate_us=1500, seq=12, step=4,
                         arrival_us=123.456)
        tl.close()
        events = json.load(open(path))
        neg = [e for e in events
               if e["name"] == "NEGOTIATE" and e["ph"] == "E"]
        args = neg[0]["args"]
        assert args["seq"] == 12 and args["step"] == 4
        assert args["tensor"] == "g0"
        assert args["arrival_us"] == 123.456
        assert args["coordinator_negotiate_us"] == 1500


# ---------------------------------------------------------------------------
# merge + straggler attribution (synthetic per-rank files)
# ---------------------------------------------------------------------------

def _write_rank_trace(path, rank, anchor_mono_ns, events,
                      clock_syncs=(), truncate=False):
    evs = [{"name": "hvd_trace_meta", "ph": "M", "pid": 0, "tid": 0,
            "args": {"rank": rank, "anchor_mono_ns": anchor_mono_ns,
                     "anchor_unix_ns": 1_700_000_000_000_000_000,
                     "version": 1}}]
    for off, rtt in clock_syncs:
        evs.append({"name": "CLOCK_SYNC", "ph": "M", "pid": 0,
                    "tid": 0, "args": {"offset_ns": off,
                                       "rtt_ns": rtt}})
    evs += events
    body = json.dumps(evs)
    if truncate:
        # what a SIGKILLed rank leaves behind: an unterminated array
        body = body[:-1].rstrip() + ","
    with open(path, "w") as f:
        f.write(body)


def _neg_end(tensor, seq, arrival_us, ts_us, tid=1):
    return {"name": "NEGOTIATE", "ph": "E", "pid": 0, "tid": tid,
            "ts": ts_us, "args": {"seq": seq, "step": 0,
                                  "tensor": tensor,
                                  "arrival_us": arrival_us}}


def _make_two_rank_dir(d):
    """Rank 1 runs on a clock anchored 1 s later with a known
    calibration offset; it arrives 42 ms late at grads_0 and on time
    at grads_1."""
    # rank 0: anchor 1e9; arrivals at 600_000 us and 700_000 us.
    _write_rank_trace(
        os.path.join(d, "tl.json"), 0, 1_000_000_000,
        [{"name": "QUEUE", "ph": "B", "pid": 0, "tid": 1,
          "ts": 500.0},
         {"name": "QUEUE", "ph": "E", "pid": 0, "tid": 1,
          "ts": 900.0},
         _neg_end("grads_0", 0, 600_000.0, 650_000.0),
         _neg_end("grads_1", 1, 700_000.0, 750_000.0)])
    # rank 1: anchor 2e9, offset -0.5e9 => shift = +500_000 us on
    # rank 0's axis. grads_0 local arrival 142_000 -> global 642_000
    # (42 ms late); grads_1 local 200_000 -> global 700_000 (on time).
    _write_rank_trace(
        os.path.join(d, "tl.rank1.json"), 1, 2_000_000_000,
        [_neg_end("grads_0", 0, 142_000.0, 160_000.0),
         _neg_end("grads_1", 1, 200_000.0, 255_000.0)],
        clock_syncs=[(-500_000_000, 40_000), (-400_000_000, 900_000)])


class TestMergeAndAttribution:
    def test_merge_aligns_clocks_and_names_straggler(self, tmp_path):
        d = str(tmp_path)
        _make_two_rank_dir(d)
        merged_path, report = tracing.merge(d)
        doc = json.load(open(merged_path))
        evs = doc["traceEvents"]
        assert {e.get("pid") for e in evs if "ts" in e} == {0, 1}
        # one process_name track per rank
        pnames = {e["pid"]: e["args"]["name"] for e in evs
                  if e.get("name") == "process_name"}
        assert pnames == {0: "rank 0", 1: "rank 1"}
        # rank 1 timestamps shifted onto rank 0's axis with the
        # MIN-RTT calibration record (-0.5 s, not the noisier -0.4 s)
        r1_neg = [e for e in evs
                  if e.get("pid") == 1 and e.get("name") == "NEGOTIATE"]
        assert r1_neg[0]["ts"] == pytest.approx(660_000.0)
        # attribution: rank 1 is the offender, 42 ms late at grads_0
        assert report["correlated_collectives"] == 2
        assert report["offenders"][0][0] == 1
        t0 = report["per_tensor"]["grads_0"]
        assert t0["worst_rank"] == 1
        assert t0["max_skew_s"] == pytest.approx(0.042, abs=1e-6)
        assert report["per_rank"]["1"]["mean_delta_s"] == \
            pytest.approx(0.021, abs=1e-6)
        assert report["per_rank"]["0"]["mean_delta_s"] == 0.0

    def test_merge_is_byte_stable(self, tmp_path):
        """Identical inputs => byte-identical merged trace and report
        (golden-file property: a re-run must not churn diffs)."""
        da, db = tmp_path / "a", tmp_path / "b"
        da.mkdir(), db.mkdir()
        _make_two_rank_dir(str(da))
        _make_two_rank_dir(str(db))
        pa, _ = tracing.merge(str(da))
        pb, _ = tracing.merge(str(db))
        assert open(pa, "rb").read() == open(pb, "rb").read()
        ra = open(os.path.join(str(da), "straggler_report.json"),
                  "rb").read()
        rb = open(os.path.join(str(db), "straggler_report.json"),
                  "rb").read()
        assert ra == rb

    def test_merge_tolerates_truncated_trace(self, tmp_path):
        """A SIGKILLed rank leaves an unterminated JSON array; the
        loader repairs it instead of dropping the rank."""
        d = str(tmp_path)
        _write_rank_trace(os.path.join(d, "tl.json"), 0, 1_000,
                          [_neg_end("g", 0, 100.0, 200.0)])
        _write_rank_trace(os.path.join(d, "tl.rank1.json"), 1, 1_000,
                          [_neg_end("g", 0, 150.0, 260.0)],
                          truncate=True)
        _, report = tracing.merge(d)
        assert report["ranks"] == [0, 1]
        assert report["correlated_collectives"] == 1

    def test_merge_missing_rank0_aligns_relative_to_base(self,
                                                         tmp_path):
        """Rank 0's trace lost: the fallback base (lowest present
        rank) must subtract ITS OWN rank-0 offset from everyone —
        otherwise the base sits displaced by its offset and dominates
        the straggler report."""
        d = str(tmp_path)
        # rank 1 (base): offset to rank 0 = +3 s.
        _write_rank_trace(
            os.path.join(d, "tl.rank1.json"), 1, 1_000_000_000,
            [_neg_end("g", 0, 100_000.0, 150_000.0)],
            clock_syncs=[(3_000_000_000, 10_000)])
        # rank 2: offset +3.005 s, same anchor; arrives 5 ms late.
        _write_rank_trace(
            os.path.join(d, "tl.rank2.json"), 2, 1_000_000_000,
            [_neg_end("g", 0, 100_000.0, 160_000.0)],
            clock_syncs=[(3_005_000_000, 10_000)])
        _, report = tracing.merge(d)
        assert report["ranks"] == [1, 2]
        t = report["per_tensor"]["g"]
        assert t["worst_rank"] == 2
        assert t["max_skew_s"] == pytest.approx(0.005, abs=1e-6)

    def test_merge_tolerates_mid_event_truncation(self, tmp_path):
        """A SIGKILL landing mid `f.write` leaves a PARTIAL last
        event (not just a missing ']'); the loader drops the damaged
        tail line and keeps the intact events."""
        d = str(tmp_path)
        _write_rank_trace(os.path.join(d, "tl.json"), 0, 1_000,
                          [_neg_end("g", 0, 100.0, 200.0)])
        meta = json.dumps(
            {"name": "hvd_trace_meta", "ph": "M", "pid": 0, "tid": 0,
             "args": {"rank": 1, "anchor_mono_ns": 1_000,
                      "anchor_unix_ns": 1, "version": 1}})
        ev = json.dumps(_neg_end("g", 0, 150.0, 260.0))
        raw = "[\n" + meta + ",\n" + ev + ',\n{"name": "NEGO'
        with open(os.path.join(d, "tl.rank1.json"), "w") as f:
            f.write(raw)
        _, report = tracing.merge(d)
        assert report["ranks"] == [0, 1]
        assert report["correlated_collectives"] == 1

    def test_merge_dir_finds_extensionless_rank0(self, tmp_path):
        """HOROVOD_TIMELINE needs no .json extension: directory-mode
        discovery must still find rank 0's extensionless file next to
        the .rankN.json siblings."""
        d = str(tmp_path)
        _write_rank_trace(os.path.join(d, "trace"), 0, 1_000,
                          [_neg_end("g", 0, 100.0, 200.0)])
        _write_rank_trace(os.path.join(d, "trace.rank1.json"), 1,
                          1_000, [_neg_end("g", 0, 150.0, 260.0)])
        _, report = tracing.merge(d)
        assert report["ranks"] == [0, 1]
        assert report["correlated_collectives"] == 1

    def test_merge_without_traces_raises(self, tmp_path):
        with pytest.raises(ValueError, match="no per-rank traces"):
            tracing.merge(str(tmp_path))

    def test_doctor_cli_renders_report(self, tmp_path, capsys):
        from horovod_tpu.runner.doctor import main as doctor_main
        d = str(tmp_path)
        _make_two_rank_dir(d)
        assert doctor_main(["trace", d]) == 0
        out = capsys.readouterr().out
        assert "rank 1" in out and "grads_0" in out
        assert doctor_main(["trace", str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# postmortem / flight-recorder dumps
# ---------------------------------------------------------------------------

class TestPostmortem:
    def test_write_postmortem_contents(self, tmp_path, monkeypatch,
                                       default_ring):
        monkeypatch.setenv("HOROVOD_TRACE_POSTMORTEM_DIR",
                           str(tmp_path))
        tracing.configure_ring(16)
        tracing.record("dispatch", "pm_op", 5)
        path = tracing.write_postmortem("unit test", trigger="manual")
        assert path == str(tmp_path / "postmortem-rank0.json")
        doc = json.load(open(path))
        assert doc["reason"] == "unit test"
        assert doc["trigger"] == "manual"
        assert any(ev[2] == "pm_op" for ev in doc["ring"])
        # thread stacks include at least this (main) thread
        assert doc["thread_stacks"]
        assert "metrics" in doc and "runtime" in doc

    def test_sigusr2_dump(self, tmp_path, monkeypatch, default_ring):
        monkeypatch.setenv("HOROVOD_TRACE_POSTMORTEM_DIR",
                           str(tmp_path))
        tracing.configure_ring(16)
        tracing.record("dispatch", "sig_op")
        assert tracing.install_signal_handler()
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.time() + 10
        path = tmp_path / "postmortem-rank0.json"
        while not path.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert path.exists()
        doc = json.load(open(str(path)))
        assert doc["trigger"] == "sigusr2"

    def test_init_survives_unwritable_timeline_dir(self, tmp_path,
                                                   default_ring):
        """A host where the trace directory is missing loses its
        trace with a warning — hvd.init() must not die for an
        observability feature. Piggybacks the config_overrides
        plumbing check: trace knobs set via init(config_overrides=)
        (not env) must reach the ring and the signal handler."""
        import horovod_tpu as hvd
        from horovod_tpu.common.basics import state
        hvd.init(config_overrides={
            "HOROVOD_TIMELINE": str(tmp_path / "nope" / "tl.json"),
            "HOROVOD_TRACE_RING_SIZE": 8,
            "HOROVOD_TRACE_POSTMORTEM_DIR": str(tmp_path)})
        try:
            assert state().timeline is None
            for i in range(20):
                tracing.record("dispatch", f"o{i}")
            assert len(tracing.ring_events()) == 8
            assert tracing.postmortem_dir() == str(tmp_path)
        finally:
            hvd.shutdown()
            tracing._cfg = None

    def test_sigusr2_respects_user_handler(self):
        """A user-installed SIGUSR2 handler (checkpoint-on-preemption
        patterns) must never be replaced."""
        was_installed = tracing._sigusr2_installed
        tracing._sigusr2_installed = False

        def user_handler(sig, frm):
            pass

        old = signal.signal(signal.SIGUSR2, user_handler)
        try:
            assert tracing.install_signal_handler() is False
            assert signal.getsignal(signal.SIGUSR2) is user_handler
        finally:
            signal.signal(signal.SIGUSR2, old)
            tracing._sigusr2_installed = was_installed

    def test_dump_verb_over_the_wire(self, tmp_path, monkeypatch):
        """The elastic control plane's dump verb: a BasicClient with
        the job secret asks a live worker for its postmortem."""
        monkeypatch.setenv("HOROVOD_TRACE_POSTMORTEM_DIR",
                           str(tmp_path))
        monkeypatch.setenv("HOROVOD_SECRET", "dump-secret")
        from horovod_tpu.elastic.worker import NotificationListener
        from horovod_tpu.runner.service import BasicClient
        lst = NotificationListener()
        try:
            cli = BasicClient("127.0.0.1", lst.port, "dump-secret",
                              timeout=5.0)
            reply = cli.request({"type": "dump"})
            assert reply["ok"] is True
            assert os.path.exists(reply["path"])
            doc = json.load(open(reply["path"]))
            assert doc["trigger"] == "dump_verb"
        finally:
            lst.stop()


# ---------------------------------------------------------------------------
# 2-rank integration: merged trace + straggler attribution
# ---------------------------------------------------------------------------

@pytest.mark.integration
def test_two_rank_merged_trace_names_slow_rank(tmp_path):
    """Acceptance path: a 2-rank run with HOROVOD_TIMELINE set and an
    injected dispatch.entry delay on rank 1 (faults.py) produces
    per-rank traces that merge into one clock-aligned Chrome trace
    containing both ranks with SHARED collective sequence ids, and
    the straggler report names the fault-injected slow rank."""
    tl_path = str(tmp_path / "tl.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_TIMELINE"] = tl_path
    # Every dispatch on rank 1 sleeps 150 ms: its NEXT submit arrives
    # late, so negotiation waits on it — the classic straggler.
    env["HOROVOD_FAULTS"] = "dispatch.entry:delay:rank=1,ms=150"
    env["HOROVOD_FAULTS_SEED"] = "0"
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, os.path.join("tests", "mp_worker_tracing.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip("this jaxlib's CPU backend cannot run cross-"
                    "process collectives (affects every multiprocess "
                    "integration test)")
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("TRACING WORKER OK") == 2

    merged_path, report = tracing.merge(tl_path)
    doc = json.load(open(merged_path))
    evs = doc["traceEvents"]
    assert {0, 1} <= {e.get("pid") for e in evs}

    # shared collective sequence ids: the same named collective got
    # the SAME seq on both ranks (assigned from the agreed order)
    by_name = {}
    for e in evs:
        args = e.get("args") or {}
        if e.get("name") == "NEGOTIATE" and e.get("ph") == "E" \
                and "seq" in args:
            by_name.setdefault(args["tensor"], {})[e["pid"]] = \
                args["seq"]
    shared = {n: v for n, v in by_name.items() if len(v) == 2}
    assert shared, by_name
    assert all(len(set(v.values())) == 1 for v in shared.values()), \
        shared
    assert any(n.startswith("grads_") for n in shared)

    # straggler attribution: the delayed rank is the top offender,
    # and its measured lateness is in the injected-delay ballpark
    assert report["offenders"][0][0] == 1, report
    assert report["per_rank"]["1"]["mean_delta_s"] > 0.03, report
    assert report["per_rank"]["1"]["mean_delta_s"] > \
        report["per_rank"]["0"]["mean_delta_s"]
    worst = {name: st for name, st in report["per_tensor"].items()
             if st["worst_rank"] == 1 and st["max_skew_s"] > 0.05}
    assert worst, report["per_tensor"]
