"""Continuous health telemetry tests: recorder shard discipline
(meta-first, schema-valid records, rates from counter deltas, bounded
ring, rotation, torn-tail tolerance), the disarmed one-load fast path
and its overhead guard, the online detectors on seeded synthetic
series (regression caught, steady series silent, runtime recovery
attribution, stall dual, cooldown), the offline `doctor health`
analyzer (byte determinism, journal-anchored recovery-window
attribution, CLI exit contract), a live decode chaos leg whose
injected crash must alert AND be attributed to the recovery, and the
committed r20 recording's byte-identity pins."""

import json
import os
import subprocess
import sys
import time

import pytest

from horovod_tpu import decoding, faults, journal, telemetry
from horovod_tpu.metrics import REGISTRY
from horovod_tpu.runner import doctor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEALTH_DIR = os.path.join(REPO, "benchmarks", "health_r20")
HEALTH_BENCH = os.path.join(REPO, "benchmarks",
                            "BENCH_health_r20.json")
TRAJECTORY = os.path.join(REPO, "benchmarks", "BENCH_trajectory.json")
COMMITTED_JOURNAL_DIRS = (
    "incident_chaos_r11", "incident_preempt_r14",
    "serving_trace_r16", "serving_decode_r18",
)


@pytest.fixture(autouse=True)
def _clean_state():
    """Recorder, journal and fault plan are process-global seams;
    restore all three so state never leaks across tests."""
    yield
    faults.configure("", seed=0)
    telemetry.disarm()
    journal.disarm()


def _env(tmp_path, **over):
    d = os.path.join(str(tmp_path), "rec")
    os.makedirs(d, exist_ok=True)
    env = {
        "HOROVOD_TELEMETRY_DIR": d,
        "HOROVOD_TELEMETRY_INTERVAL_S": "0",
        "HOROVOD_JOURNAL_DIR": d,
        # Defaults for tests that are NOT about the wall-clock
        # detectors: tight python loops have genuinely jittery beat
        # periods, so park the MAD/stall thresholds out of reach and
        # let each detector test re-arm the one it targets.
        "HOROVOD_TELEMETRY_STEP_MAD_K": "1e9",
        "HOROVOD_TELEMETRY_STALL_FLOOR_S": "1e9",
    }
    env.update({k: str(v) for k, v in over.items()})
    return env, d


def _arm(tmp_path, rank=0, **over):
    env, d = _env(tmp_path, **over)
    journal.configure("worker", rank, env=env)
    rec = telemetry.configure("worker", rank, env=env)
    assert rec is not None
    return rec, d


def _shard_events(d, rank=0):
    evs, dropped = journal.read_journal(
        os.path.join(d, f"telemetry-rank{rank}.jsonl"))
    return evs, dropped


def _alerts(d):
    evs, _ = journal.load_journals(d)
    return [e for e in evs if e.get("type") == "health_alert"]


class TestRecorder:
    def test_disarmed_beat_is_inert(self):
        assert not telemetry.enabled()
        telemetry.beat("commit")          # must not raise
        telemetry.beat("decode", key="w0")

    def test_disarmed_fast_path_overhead(self):
        """The unconditional-call contract: disarmed beat() is one
        module load + compare, cheap enough for hot loops."""
        assert telemetry.get() is None
        t0 = time.perf_counter()
        for _ in range(100_000):
            telemetry.beat("decode", key="w0")
        dt = time.perf_counter() - t0
        assert dt < 1.0, f"100k disarmed beats took {dt:.3f}s"

    def test_meta_first_and_records_schema_valid(self, tmp_path):
        rec, d = _arm(tmp_path)
        c = REGISTRY.counter("hvdtest_tel_ticks_total", "seeded")
        for _ in range(5):
            c.inc()
            telemetry.beat("commit")
        telemetry.disarm()
        evs, dropped = _shard_events(d)
        assert dropped == 0
        # journal_meta (the Journal writer's own anchor record) then
        # telemetry_meta, then samples — and every record validates
        # against the declared EVENT_SCHEMAS.
        types = [e["type"] for e in evs]
        assert types[0] == "journal_meta"
        assert types[1] == "telemetry_meta"
        assert types.count("telemetry_sample") == 5
        for e in evs:
            assert journal.validate_event(e) == [], e["type"]
        meta = evs[1]
        assert meta["schema"] == telemetry.TELEMETRY_SCHEMA
        assert meta["interval_s"] == 0.0

    def test_counter_deltas_become_rates(self, tmp_path):
        rec, d = _arm(tmp_path)
        c = REGISTRY.counter("hvdtest_tel_rate_total", "seeded")
        telemetry.beat("commit")          # baseline sample
        c.inc(10)
        time.sleep(0.05)                  # a measurable dt
        telemetry.beat("commit")
        telemetry.disarm()
        evs, _ = _shard_events(d)
        samples = [e for e in evs if e["type"] == "telemetry_sample"]
        assert len(samples) == 2
        last = samples[-1]
        key = "hvdtest_tel_rate_total"
        assert last["rates"][key] > 0
        assert last["dt_s"] > 0
        # rate * dt recovers the delta
        assert last["rates"][key] * last["dt_s"] == pytest.approx(
            10.0, rel=0.05)
        # per-beat counts since the previous sample
        assert last["beats"] == {"commit": 1}

    def test_gauges_recorded_raw(self, tmp_path):
        rec, d = _arm(tmp_path)
        g = REGISTRY.gauge("hvdtest_tel_depth", "seeded")
        g.set(7.5)
        telemetry.beat("serving")
        telemetry.disarm()
        evs, _ = _shard_events(d)
        s = [e for e in evs if e["type"] == "telemetry_sample"][-1]
        assert s["gauges"]["hvdtest_tel_depth"] == 7.5

    def test_hist_deltas_mean(self, tmp_path):
        rec, d = _arm(tmp_path)
        h = REGISTRY.histogram("hvdtest_tel_lat_seconds", "seeded")
        telemetry.beat("commit")
        h.observe(0.2)
        h.observe(0.4)
        telemetry.beat("commit")
        telemetry.disarm()
        evs, _ = _shard_events(d)
        s = [e for e in evs if e["type"] == "telemetry_sample"][-1]
        ent = s["hist"]["hvdtest_tel_lat_seconds"]
        assert ent["n"] == 2
        assert ent["mean_s"] == pytest.approx(0.3, abs=1e-6)

    def test_ring_bounded(self, tmp_path):
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_RING=8)
        for _ in range(40):
            telemetry.beat("commit")
        ring = rec.snapshot_ring()
        assert len(ring) == 8
        assert ring[-1]["seq"] == 39

    def test_rotation_rolls_to_sibling(self, tmp_path):
        rec, d = _arm(tmp_path)
        rec._journal._rotate_bytes = 4096
        for _ in range(200):
            telemetry.beat("commit")
        telemetry.disarm()
        assert os.path.exists(
            os.path.join(d, "telemetry-rank0.jsonl.1"))
        # rotated sibling + live segment both load, time-ordered; the
        # latest sample survives (older rotated-away segments may not)
        evs, _ = telemetry.load_telemetry(d)
        seqs = [e["seq"] for e in evs
                if e["type"] == "telemetry_sample"]
        assert seqs and seqs == sorted(seqs)
        assert seqs[-1] == 199

    def test_interval_batches_beats(self, tmp_path):
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_INTERVAL_S=3600)
        for _ in range(50):
            telemetry.beat("decode", key="w0")
        telemetry.disarm()
        evs, _ = _shard_events(d)
        samples = [e for e in evs if e["type"] == "telemetry_sample"]
        assert len(samples) == 1  # the first beat's baseline sample

    def test_configure_unset_dir_noop(self):
        assert telemetry.configure("worker", 0, env={}) is None
        assert not telemetry.enabled()


class TestDetectors:
    def test_step_time_regression_caught(self, tmp_path):
        """Seeded synthetic series: a stable histogram mean that
        steps up must alert within 3 anomalous samples."""
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_STEP_MAD_K="8")
        h = REGISTRY.histogram("hvdtest_reg_step_seconds", "seeded")
        for _ in range(8):                 # baseline
            h.observe(0.1)
            telemetry.beat("bench")
        for _ in range(4):                 # regression
            h.observe(1.0)
            telemetry.beat("bench")
        telemetry.disarm()
        hits = [a for a in _alerts(d)
                if a["signal"]
                == "hist_mean:hvdtest_reg_step_seconds"]
        assert hits, f"no regression alert in {_alerts(d)}"
        a = hits[0]
        assert a["detector"] == "step_time_regression"
        assert a["value"] > a["threshold"] > a["baseline"]
        assert "attributed" not in a       # steady state: an anomaly

    def test_steady_series_zero_false_alerts(self, tmp_path):
        """Seeded jitter around a stable mean stays silent."""
        import random
        rng = random.Random(20)
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_STEP_MAD_K="8")
        h = REGISTRY.histogram("hvdtest_steady_seconds", "seeded")
        for _ in range(64):
            h.observe(0.1 + rng.uniform(-0.004, 0.004))
            telemetry.beat("bench")
        telemetry.disarm()
        assert [a for a in _alerts(d)
                if a["signal"]
                == "hist_mean:hvdtest_steady_seconds"] == []

    def test_beat_stall_detected(self, tmp_path):
        """A source that stops beating is caught by its peers'
        samples — the form a hard-stopped worker takes."""
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_STEP_MAD_K="8",
                      HOROVOD_TELEMETRY_STALL_FLOOR_S="0.05")
        for _ in range(10):
            telemetry.beat("decode", key="a")
            telemetry.beat("decode", key="b")
            time.sleep(0.002)
        time.sleep(0.3)                    # b dies; a keeps ticking
        for _ in range(3):
            telemetry.beat("decode", key="a")
            time.sleep(0.002)
        telemetry.disarm()
        sigs = {a["signal"] for a in _alerts(d)}
        assert "beat_stall:decode/b" in sigs
        assert "beat_stall:decode/a" not in sigs

    def test_queue_growth_alerts_with_floor(self, tmp_path):
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_QUEUE_MIN=8,
                      HOROVOD_TELEMETRY_TREND_RUN=3)
        g = REGISTRY.gauge("hvd_serving_queue_depth", "depth")
        for v in [1, 2, 3, 2, 3, 4]:       # grows but under floor
            g.set(float(v))
            telemetry.beat("serving")
        assert _alerts(d) == []
        for v in [6, 9, 12, 15]:           # grows past the floor
            g.set(float(v))
            telemetry.beat("serving")
        telemetry.disarm()
        g.set(0.0)                      # don't leak into later tests
        hits = [a for a in _alerts(d)
                if a["detector"] == "queue_depth_growth"]
        assert hits and hits[0]["value"] >= 8

    def test_slo_burst_alerts(self, tmp_path):
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_SLO_BURST=5)
        c = REGISTRY.counter("hvdtest_tel_slo_miss_total", "seeded",
                             ("slo",))
        telemetry.beat("serving")          # baseline
        c.labels(slo="interactive").inc(2)
        telemetry.beat("serving")          # under burst: silent
        assert _alerts(d) == []
        c.labels(slo="interactive").inc(7)
        telemetry.beat("serving")
        telemetry.disarm()
        hits = [a for a in _alerts(d)
                if a["detector"] == "slo_miss_burst"]
        assert hits and hits[0]["value"] == 7.0

    def test_staleness_runaway_alerts(self, tmp_path):
        rec, d = _arm(tmp_path,
                      HOROVOD_TELEMETRY_STALENESS_LIMIT=50)
        g = REGISTRY.gauge("hvd_weights_staleness_steps", "lag",
                           ("worker",))
        for v in [10, 30, 49]:
            g.labels(worker="w0").set(float(v))
            telemetry.beat("weights", key="w0")
        assert _alerts(d) == []
        g.labels(worker="w0").set(80.0)
        telemetry.beat("weights", key="w0")
        telemetry.disarm()
        g.labels(worker="w0").set(0.0)
        hits = [a for a in _alerts(d)
                if a["detector"] == "weight_staleness_runaway"]
        assert hits and hits[0]["value"] == 80.0

    def test_stuck_high_gauge_is_not_runaway(self, tmp_path):
        """A staleness gauge already past the limit when the recorder
        arms (and never climbing again) must NOT alert: runaway means
        observed climbing, not a stale leftover level."""
        g = REGISTRY.gauge("hvd_weights_staleness_steps", "lag",
                           ("worker",))
        g.labels(worker="w0").set(80.0)
        rec, d = _arm(tmp_path,
                      HOROVOD_TELEMETRY_STALENESS_LIMIT=50)
        for _ in range(6):
            telemetry.beat("weights", key="w0")
        telemetry.disarm()
        g.labels(worker="w0").set(0.0)
        assert _alerts(d) == []

    def test_runtime_recovery_attribution(self, tmp_path):
        """An alert raised while a recovery signal is moving carries
        attributed="recovery" — expected fallout, not an anomaly."""
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_STEP_MAD_K="8")
        h = REGISTRY.histogram("hvdtest_attr_step_seconds", "seeded")
        recov = REGISTRY.counter("hvd_recoveries_total",
                                 "recoveries", ("cause",))
        for _ in range(8):
            h.observe(0.1)
            telemetry.beat("bench")
        recov.labels(cause="crash").inc()  # recovery in flight
        for _ in range(4):
            h.observe(1.0)
            telemetry.beat("bench")
        telemetry.disarm()
        hits = [a for a in _alerts(d)
                if a["signal"]
                == "hist_mean:hvdtest_attr_step_seconds"]
        assert hits
        assert all(a.get("attributed") == "recovery" for a in hits)

    def test_prearm_recovery_totals_are_history(self, tmp_path):
        """A recovery counter that was already nonzero when the
        recorder armed is history, not a recovery in flight: the
        baseline sample must not treat pre-arm totals as deltas, or
        every alert in the first grace period gets falsely attributed
        (the long-lived-process shape: telemetry armed mid-life)."""
        recov = REGISTRY.counter("hvd_recoveries_total",
                                 "recoveries", ("cause",))
        recov.labels(cause="crash").inc()  # ancient, pre-arm
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_STEP_MAD_K="8")
        h = REGISTRY.histogram("hvdtest_hist_step_seconds", "seeded")
        for _ in range(8):
            h.observe(0.1)
            telemetry.beat("bench")
        for _ in range(4):
            h.observe(1.0)
            telemetry.beat("bench")
        telemetry.disarm()
        hits = [a for a in _alerts(d)
                if a["signal"]
                == "hist_mean:hvdtest_hist_step_seconds"]
        assert hits
        assert all("attributed" not in a for a in hits)

    def test_alert_cooldown(self, tmp_path):
        rec, d = _arm(tmp_path, HOROVOD_TELEMETRY_SLO_BURST=1,
                      HOROVOD_TELEMETRY_ALERT_COOLDOWN_S=3600)
        c = REGISTRY.counter("hvdtest_cool_slo_miss_total", "seeded")
        telemetry.beat("serving")
        for _ in range(6):                 # persisting burst
            c.inc(5)
            telemetry.beat("serving")
        telemetry.disarm()
        hits = [a for a in _alerts(d)
                if a["signal"] == "rate:hvdtest_cool_slo_miss_total"]
        assert len(hits) == 1              # cooled down, not flooded


class TestOfflineReport:
    def _synthetic(self, d):
        """Hand-written shards with controlled timestamps: a steady
        run, one journaled fault at t=100 with an attributed alert
        beside it, and one far-from-anything anomaly at t=200."""
        def w(path, recs):
            with open(os.path.join(d, path), "w") as f:
                for i, r in enumerate(recs):
                    r.setdefault("role", "worker")
                    r.setdefault("rank", 0)
                    r.setdefault("pid", 1)
                    r.setdefault("mono_ns", int(r["t"] * 1e9))
                    r["n"] = i
                    f.write(json.dumps(r, sort_keys=True) + "\n")
        samples = [{"type": "telemetry_sample", "t": 10.0 + i,
                    "beat": "commit", "seq": i, "dt_s": 1.0,
                    "beats": {"commit": 1},
                    "rates": {"hvd_x_total": 4.0},
                    "gauges": {"hvd_depth": float(i % 3)},
                    "hist": {"hvd_step_seconds":
                             {"n": 1, "mean_s": 0.1}}}
                   for i in range(200)]
        meta = [{"type": "telemetry_meta", "t": 9.0,
                 "schema": telemetry.TELEMETRY_SCHEMA,
                 "anchor_mono_ns": 0, "anchor_unix": 9.0,
                 "host": "h", "interval_s": 1.0, "ring": 512}]
        w("telemetry-rank0.jsonl", meta + samples)
        alert = {"detector": "step_time_regression", "beat": "commit",
                 "signal": "hist_mean:hvd_step_seconds",
                 "value": 1.0, "baseline": 0.1, "threshold": 0.2,
                 "window": 16}
        jrecs = [
            {"type": "fault_fired", "t": 100.0, "point": "x",
             "action": "error"},
            dict(alert, type="health_alert", t=102.0),
            dict(alert, type="health_alert", t=200.0),
        ]
        w("journal-rank0.jsonl", jrecs)

    def test_window_attribution_and_anomaly(self, tmp_path):
        d = str(tmp_path)
        self._synthetic(d)
        rep = telemetry.health_report(d)
        assert rep["summary"]["alerts"] == 2
        assert rep["summary"]["attributed_alerts"] == 1
        assert rep["summary"]["anomalies"] == 1
        attributed = [a for a in rep["alerts"]
                      if not a["anomaly"]]
        assert attributed[0]["recovery_window"] == 0
        wins = rep["recovery_windows"]
        assert len(wins) == 1
        assert wins[0]["anchors"] == ["fault_fired"]
        # grace is the FIXED analyzer constant, not an env knob
        assert (wins[0]["t_end"] - wins[0]["t_begin"]
                == pytest.approx(2 * telemetry.RECOVERY_GRACE_S))

    def test_steady_vs_recovery_decomposition(self, tmp_path):
        d = str(tmp_path)
        self._synthetic(d)
        rep = telemetry.health_report(d)
        sig = rep["signals"]["hist_mean:hvd_step_seconds"]
        assert sig["all"]["n"] == 200
        # samples inside the fault window decompose into "recovery"
        assert sig["recovery"]["n"] > 0
        assert (sig["steady"]["n"] + sig["recovery"]["n"]
                == sig["all"]["n"])
        assert rep["beats"] == {"commit": 200}

    def test_byte_determinism(self, tmp_path):
        d = str(tmp_path)
        self._synthetic(d)
        p1, _ = telemetry.write_health_report(d)
        with open(p1, "rb") as f:
            b1 = f.read()
        p2, _ = telemetry.write_health_report(
            d, out=os.path.join(d, "again.json"))
        with open(p2, "rb") as f:
            assert b1 == f.read()
        raw = b1.decode()
        assert d not in raw                # no absolute paths

    def test_torn_tail_tolerated(self, tmp_path):
        d = str(tmp_path)
        self._synthetic(d)
        with open(os.path.join(d, "telemetry-rank0.jsonl"),
                  "a") as f:
            f.write('{"type": "telemetry_sample", "t": 999')  # torn
        rep = telemetry.health_report(d)
        assert rep["sources"][0]["repaired_tail_lines"] == 1
        assert rep["summary"]["samples"] == 200

    def test_no_shards_raises(self, tmp_path):
        with pytest.raises(ValueError, match="no telemetry shards"):
            telemetry.health_report(str(tmp_path))

    def test_render_mentions_anomaly(self, tmp_path):
        d = str(tmp_path)
        self._synthetic(d)
        txt = telemetry.render_health_report(
            telemetry.health_report(d))
        assert "ANOMALY" in txt
        assert "attributed" in txt

    def test_health_digest_disarmed_and_armed(self, tmp_path):
        assert telemetry.health_digest(str(tmp_path)) \
            == {"enabled": False}
        d = str(tmp_path)
        self._synthetic(d)
        dig = telemetry.health_digest(d)
        assert dig["enabled"] is True
        assert dig["samples"] == 200
        assert dig["alerts_by_detector"] \
            == {"step_time_regression": 2}


class TestDoctorHealthCLI:
    def test_exit_contract(self, tmp_path, capsys):
        assert doctor.main(["health", "/nonexistent"]) == 1
        assert "doctor health:" in capsys.readouterr().out
        assert doctor.main(["health", str(tmp_path)]) == 1
        assert "doctor health:" in capsys.readouterr().out

    def test_success_prints_report_path(self, tmp_path, capsys):
        d = str(tmp_path)
        TestOfflineReport()._synthetic(d)
        assert doctor.main(["health", d]) == 0
        out = capsys.readouterr().out
        assert "health report" in out
        assert "report: " in out
        assert os.path.exists(os.path.join(d, "health_report.json"))


def _decode_env(tmp_path, **over):
    d = os.path.join(str(tmp_path), "rec")
    os.makedirs(d, exist_ok=True)
    env = {
        "HOROVOD_KV_PAGE_TOKENS": "8",
        "HOROVOD_KV_MAX_CONTEXT": "64",
        "HOROVOD_SERVING_DECODE_SLOTS": "4",
        "HOROVOD_SERVING_DECODE_MAX_NEW_TOKENS": "16",
        "HOROVOD_SERVING_DECODE_WATERMARK_STRIDE": "4",
        "HOROVOD_SERVING_DECODE_LEASE_TIMEOUT_S": "2.0",
        "HOROVOD_SERVING_DECODE_RETRY_BACKOFF_MS": "5",
        "HOROVOD_JOURNAL_DIR": d,
        "HOROVOD_TELEMETRY_DIR": d,
        "HOROVOD_TELEMETRY_INTERVAL_S": "0",
    }
    env.update({k: str(v) for k, v in over.items()})
    return env, d


class TestChaosAttribution:
    def test_steady_decode_run_zero_alerts(self, tmp_path):
        """Healthy single-worker decode drain: telemetry records the
        run but no detector fires (tuned-but-plausible thresholds)."""
        env, d = _decode_env(tmp_path,
                             HOROVOD_TELEMETRY_STEP_MAD_K="30",
                             HOROVOD_TELEMETRY_STALL_FLOOR_S="5.0")
        fe = decoding.DecodeFrontend(workers=1, env=env,
                                     trace_tag="steady")
        try:
            futs = [fe.submit([1, 2, 3], max_new_tokens=24, seed=s)
                    for s in range(4)]
            for f in futs:
                list(f.result(timeout=120))
        finally:
            fe.close()
        telemetry.disarm()
        journal.disarm()
        assert _alerts(d) == []
        rep = telemetry.health_report(d)
        assert rep["summary"]["samples"] > 0
        assert rep["summary"]["anomalies"] == 0

    def test_injected_hang_alerts_and_attributes(self, tmp_path):
        """The tentpole chaos leg: an injected decode.step hang
        parks the victim past the lease timeout, so its beats stall
        while the survivor keeps sampling; those samples raise a
        beat_stall health_alert, and the attribution paths (runtime
        recovery flag from the moved fault counter, offline
        journal-anchored windows) explain it — zero anomalies in the
        final report. (An in-process *error* is detected and resumed
        immediately, leaving no stall window — the hang is the shape
        the stall detector exists for.)"""
        env, d = _decode_env(tmp_path,
                             HOROVOD_TELEMETRY_STEP_MAD_K="10")
        faults.configure("decode.step:hang:at=12", seed=0)
        fe = decoding.DecodeFrontend(workers=2, env=env,
                                     trace_tag="chaos")
        fe.start_watchdog()
        try:
            futs = [fe.submit([1, 2, 3], max_new_tokens=40, seed=s)
                    for s in range(2)]
            for f in futs:
                list(f.result(timeout=120))
            assert fe.stats()["resumed"] >= 1
        finally:
            fe.close()
        telemetry.disarm()
        journal.disarm()
        alerts = _alerts(d)
        stalls = [a for a in alerts
                  if a["signal"].startswith("beat_stall:decode/")]
        assert stalls, f"no stall alert; alerts={alerts}"
        rep = telemetry.health_report(d)
        assert rep["summary"]["alerts"] >= 1
        assert rep["summary"]["anomalies"] == 0
        assert rep["summary"]["attributed_alerts"] \
            == rep["summary"]["alerts"]
        assert rep["summary"]["recovery_windows"] >= 1


@pytest.mark.skipif(not os.path.isdir(HEALTH_DIR),
                    reason="committed health recording not present")
class TestCommittedRecording:
    def test_committed_journals_still_validate(self):
        """Satellite pin: the new schema entries must not invalidate
        any committed artifact journal."""
        for name in COMMITTED_JOURNAL_DIRS:
            d = os.path.join(REPO, "benchmarks", name)
            evs, _ = journal.load_journals(d)
            for e in evs:
                assert journal.validate_event(e) == [], (name, e)

    def test_recording_regenerates_byte_identically(self, tmp_path):
        with open(os.path.join(HEALTH_DIR, "health_report.json"),
                  "rb") as f:
            committed = f.read()
        out = os.path.join(str(tmp_path), "regen.json")
        path, _ = telemetry.write_health_report(HEALTH_DIR, out=out)
        with open(path, "rb") as f:
            assert f.read() == committed

    def test_committed_chaos_attribution(self):
        rep = telemetry.health_report(HEALTH_DIR)
        s = rep["summary"]
        assert s["alerts"] >= 1
        assert s["anomalies"] == 0
        assert s["attributed_alerts"] == s["alerts"]
        assert s["recovery_windows"] >= 1
        assert any(a["signal"].startswith("beat_stall:decode/")
                   for a in rep["alerts"])

    def test_bench_doc_pins(self):
        with open(HEALTH_BENCH) as f:
            doc = json.load(f)
        assert doc["health"]["enabled"] is True
        assert doc["health"]["anomalies"] == 0
        assert doc["health"]["alerts"] >= 1
        legs = {leg["name"] for leg in doc["legs"]}
        assert {"steady", "chaos"} <= legs

    def test_trajectory_row(self):
        with open(TRAJECTORY) as f:
            doc = json.load(f)
        assert "r20_health" in doc
        assert doc["r20_health"]["anomalies"] == 0

    @pytest.mark.integration
    def test_bench_cli_regenerates_byte_identically(self, tmp_path):
        with open(os.path.join(HEALTH_DIR, "health_report.json"),
                  "rb") as f:
            committed = f.read()
        out = os.path.join(str(tmp_path), "regen.json")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_HEALTH_REPORT_OUT"] = out
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--health-report"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        with open(out, "rb") as f:
            assert f.read() == committed
