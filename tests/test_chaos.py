"""Chaos tests: the elastic stack under injected fault schedules
(HOROVOD_FAULTS through the real seams — wire frames, rendezvous HTTP,
discovery polls, commit boundaries). Real subprocesses, no mocks, same
harness as test_elastic.py.

Two tiers: fast FIXED-SEED schedules run in tier-1 (a rotted fault
seam or recovery path fails CI immediately), and a randomized soak is
marked `slow` for the long lane."""

import os
import subprocess
import sys
import time

import pytest

from tests.test_elastic import (REPO, launch, make_env, read_logs,
                                write_discovery)

_NO_MULTIPROC = ("this jaxlib's CPU backend cannot run cross-process "
                 "collectives (affects every multiprocess "
                 "integration test)")


@pytest.fixture(scope="module")
def multiproc_backend():
    """Cheap capability probe, shared by the chaos runs: one tiny
    2-rank allreduce. Without it, an incapable backend (the same gate
    test_metrics.py skips on) would burn a full reset-limit's worth
    of gang restarts PER chaos test before we could tell."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c",
         "import jax.numpy as jnp; import horovod_tpu as hvd; "
         "hvd.init(); hvd.allreduce(jnp.ones(4), name='probe'); "
         "hvd.shutdown()"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip(_NO_MULTIPROC)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


def _skip_if_no_multiproc(out, returncode):
    """In-run fallback for the same capability gate."""
    if returncode != 0 and \
            "Multiprocess computations aren't implemented" in out:
        pytest.skip(_NO_MULTIPROC)


def _chaos_env(tmp_path, steps, sleep, spec, seed=7, heartbeat=None):
    env = make_env(tmp_path, steps=steps, sleep=sleep)
    env["HOROVOD_FAULTS"] = spec
    env["HOROVOD_FAULTS_SEED"] = str(seed)
    env["HOROVOD_LOG_LEVEL"] = "info"
    if heartbeat is not None:
        env["HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT"] = str(heartbeat)
    return env


@pytest.mark.integration
class TestChaosFixedSeed:
    def test_crash_at_step_gang_restart(self, tmp_path, multiproc_backend):
        """Injected crash-at-step-N (rank 1 hard-exits inside its 4th
        commit) plus low-probability wire drops: the driver
        gang-restarts and the job trains to completion, with the fired
        fault and the reset visible in the captured logs."""
        script = write_discovery(tmp_path, "echo localhost:2")
        latch = str(tmp_path / "crash.latch")
        env = _chaos_env(
            tmp_path, steps=12, sleep=0.15,
            spec=(f"elastic.step:crash:at=4,rank=1,once={latch};"
                  "wire.send:drop:p=0.1"))
        p = launch(script, env, extra=("--reset-limit", "3"))
        out, _ = p.communicate(timeout=420)
        _skip_if_no_multiproc(out, p.returncode)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) == 2, (lines, out)
        # the schedule fired: the crash was injected (not a natural
        # death) and the driver recorded exactly one reset for it
        assert "faults: firing crash at elastic.step" in out, out
        assert os.path.exists(latch), "crash latch never created"
        assert "worker failure" in out, out
        assert "(reset 1)" in out, out
        # progress preservation across the injected crash: the rank
        # died inside commit 4, so the snapshot holds step >= 3 and
        # "step 1" may only ever come from the first incarnation
        step1 = [ln for ln in lines if ln.startswith("step 1 ")]
        assert len(step1) <= 2, (step1, lines)

    def test_hung_worker_detected_and_gang_restarted(self, tmp_path, multiproc_backend):
        """Injected livelock: rank 1 parks forever (heartbeat pacer
        stopped, the signature of a worker hung while holding
        everything). The liveness detector sees the stale heartbeat,
        kills the worker, and the ordinary hard-failure path restarts
        the gang — the job completes instead of stalling forever."""
        script = write_discovery(tmp_path, "echo localhost:2")
        latch = str(tmp_path / "hang.latch")
        env = _chaos_env(
            tmp_path, steps=10, sleep=0.1,
            spec=f"elastic.step:hang:at=3,rank=1,once={latch}",
            heartbeat=4)
        p = launch(script, env, extra=("--reset-limit", "3"))
        out, _ = p.communicate(timeout=420)
        _skip_if_no_multiproc(out, p.returncode)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) == 2, (lines, out)
        assert "faults: firing hang at elastic.step" in out, out
        assert "heartbeat stale" in out, out
        assert "killing hung worker" in out, out
        assert "worker failure" in out, out


def test_faults_disabled_is_default_noop(tmp_path, hvd_single):
    """With HOROVOD_FAULTS unset the seams are inert: a normal
    allreduce fires nothing and the fired counter stays flat (the
    per-call overhead bound lives in test_faults.py)."""
    import jax.numpy as jnp
    from horovod_tpu import faults
    from horovod_tpu.metrics import REGISTRY
    assert not faults.active()
    snap_before = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
    hvd_single.allreduce(jnp.ones(64), name="noop_chaos")
    snap_after = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
    assert snap_before == snap_after


@pytest.mark.slow
@pytest.mark.integration
@pytest.mark.parametrize("seed", [1, 2])
def test_chaos_soak_randomized_schedule(tmp_path, seed, multiproc_backend):
    """Randomized (but seeded, hence replayable) soak: probabilistic
    wire drops, flaky rendezvous HTTP, discovery outages, dispatch
    delays, AND a deterministic crash — all at once, against a live
    2-rank elastic run with the liveness detector armed. The job must
    still train to completion. On failure, re-run with the printed
    spec + seed to reproduce the exact schedule."""
    script = write_discovery(tmp_path, "echo localhost:2")
    latch = str(tmp_path / f"soak{seed}.latch")
    spec = (f"elastic.step:crash:at=5,rank=1,once={latch};"
            "wire.send:drop:p=0.1;"
            "rendezvous.http:error:p=0.1;"
            "discovery.poll:error:p=0.2;"
            "dispatch.entry:delay:ms=20,p=0.05")
    env = _chaos_env(tmp_path, steps=16, sleep=0.15, spec=spec,
                     seed=seed, heartbeat=8)
    p = launch(script, env, extra=("--reset-limit", "6"))
    t0 = time.time()
    out, _ = p.communicate(timeout=540)
    _skip_if_no_multiproc(out, p.returncode)
    assert p.returncode == 0, (
        f"soak failed (reproduce: HOROVOD_FAULTS={spec!r} "
        f"HOROVOD_FAULTS_SEED={seed})\n{out}")
    lines = read_logs(tmp_path)
    assert sum("done" in ln for ln in lines) == 2, (lines, out)
    assert "faults: firing" in out, out
    print(f"soak seed={seed} survived in {time.time() - t0:.0f}s")
