"""Elastic training worker for integration tests: a toy training loop
under hvd.elastic.run that logs (epoch-world-size, step) progress to a
file per rank, commits every step, and exits after N total steps
(reference: the elastic integration scripts in test/integration/
elastic_common.py — progress-logging training driven by a rewritable
discovery script)."""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

LOG = os.environ["ELASTIC_TEST_LOG"]
TOTAL_STEPS = int(os.environ.get("ELASTIC_TEST_STEPS", "40"))
STEP_SLEEP = float(os.environ.get("ELASTIC_TEST_SLEEP", "0.2"))


def log_line(msg):
    with open(f"{LOG}.{os.environ.get('HOROVOD_RANK', '?')}", "a") as f:
        f.write(msg + "\n")


DIE_AT = int(os.environ.get("ELASTIC_TEST_DIE_AT", "0"))


def main():
    hvd.init()
    state = hvd.elastic.JaxState(
        params={"w": jnp.zeros((2,))}, step=0,
        snapshot_path=f"{LOG}_snapshot.bin")

    # ELASTIC_TEST_WIDE=1: every step ALSO runs a bucket big enough
    # for the device-spanning ('proc','dev') path and asserts it
    # engaged with the CURRENT world size — resizes must rebuild the
    # wide mesh, not reuse a stale pre-resize one (the caches live on
    # ProcessSet instances, which re-init replaces).
    wide = os.environ.get("ELASTIC_TEST_WIDE") == "1"

    @hvd.elastic.run
    def train(state):
        while state.step < TOTAL_STEPS:
            # one "training step": an allreduce so failures/resizes
            # surface as collective errors
            g = hvd.allreduce(jnp.ones((2,)) * (state.step + 1),
                              name="grad")
            if wide:
                import jax
                from horovod_tpu.ops import dispatch
                big = hvd.allreduce(jnp.full((4096,), 1.0), name="big",
                                    op=hvd.Sum)
                np.testing.assert_allclose(
                    np.asarray(big), np.full(4096, float(hvd.size())))
                info = dispatch.last_allreduce_info()
                ndev = len(jax.local_devices())
                if hvd.size() > 1 and ndev > 1:
                    assert info.get("path") == "wide", info
                    assert info.get("mesh_shape") == {
                        "proc": hvd.size(), "dev": ndev}, (
                        info, hvd.size())
                    log_line(f"wide ok world {hvd.size()} "
                             f"devs {info['devices']}")
            state.params["w"] = state.params["w"] + np.asarray(g)
            state.step += 1
            log_line(f"step {state.step} world {hvd.size()} "
                     f"rank {hvd.rank()}")
            # failure injection (once): rank 1 dies hard at DIE_AT
            marker = f"{LOG}_died.marker"
            if (DIE_AT and state.step == DIE_AT and hvd.rank() == 1
                    and not os.path.exists(marker)):
                with open(marker, "w") as f:
                    f.write("died\n")
                os._exit(17)
            state.check_host_updates()
            state.commit()
            time.sleep(STEP_SLEEP)

    train(state)
    log_line(f"done world {hvd.size()} rank {hvd.rank()} "
             f"w0 {float(state.params['w'][0]):.1f}")
    # Chaos-test accounting: how many injected faults THIS incarnation
    # fired and how many elastic resets it survived (processes killed
    # mid-schedule obviously don't reach this line — their fires show
    # up in the driver-captured "faults: firing" log lines instead).
    snap = hvd.metrics()
    fired = sum((snap.get("hvd_faults_fired_total") or {}).values())
    resets = (snap.get("hvd_elastic_resets_total") or {}).get((), 0)
    log_line(f"stats rank {hvd.rank()} faults {int(fired)} "
             f"resets {int(resets)}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
