"""Continuous-batching decode tests: KV-ladder determinism and the
flat compile pin under cache growth, the per-(sequence, epoch)
exactly-once token latch, watermark monotonicity in the journal,
re-prefill equivalence (resumed logits bitwise-match an uninterrupted
decode at the same seed), SLO-lane shedding, admission work-stealing,
injected `decode.step` fault recovery, a real-process mid-SEQUENCE
worker kill over the lease/emit wire (zero dropped sequences, zero
re-emitted tokens), and the `doctor serve` decode-lane extension with
its byte-identity pin on the committed r16 artifact."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu import decoding, faults, journal
from horovod_tpu.common import config
from horovod_tpu.decoding import (DecodeEngine, DecodeError,
                                  DecodeFrontend, SequenceFuture,
                                  _SeqSpec, build_kv_ladder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R16_DIR = os.path.join(REPO, "benchmarks", "serving_trace_r16")
R16_ARTIFACT = os.path.join(REPO, "benchmarks",
                            "SERVING_ATTRIBUTION_r16.json")
CHAOS_WORKER = os.path.join(REPO, "tests", "decode_chaos_worker.py")
R18_DIR = os.path.join(REPO, "benchmarks", "serving_decode_r18")
R18_ARTIFACT = os.path.join(REPO, "benchmarks",
                            "SERVING_ATTRIBUTION_r18.json")
R18_BENCH = os.path.join(REPO, "benchmarks",
                         "BENCH_serving_decode_r18.json")
TRAJECTORY = os.path.join(REPO, "benchmarks", "BENCH_trajectory.json")


@pytest.fixture(autouse=True)
def _clean_fault_and_journal_state():
    """Frontends (re)configure the module journal and tests arm the
    fault plan; restore both so state never leaks across tests."""
    yield
    faults.configure("", seed=0)
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None


def _env(tmp_path=None, **over):
    env = {
        "HOROVOD_KV_PAGE_TOKENS": "8",
        "HOROVOD_KV_MAX_CONTEXT": "64",
        "HOROVOD_SERVING_DECODE_SLOTS": "4",
        "HOROVOD_SERVING_DECODE_MAX_NEW_TOKENS": "16",
        "HOROVOD_SERVING_DECODE_WATERMARK_STRIDE": "4",
        "HOROVOD_SERVING_DECODE_LEASE_TIMEOUT_S": "2.0",
        "HOROVOD_SERVING_DECODE_RETRY_BACKOFF_MS": "5",
    }
    if tmp_path is not None:
        jdir = os.path.join(str(tmp_path), "journal")
        os.makedirs(jdir, exist_ok=True)
        env["HOROVOD_JOURNAL_DIR"] = jdir
    env.update({k: str(v) for k, v in over.items()})
    return env


def _journal_events(tmp_path, role):
    path = os.path.join(str(tmp_path), "journal",
                        f"journal-{role}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _drain(fe, futs, timeout=120):
    return [list(f.result(timeout=timeout)) for f in futs]


# -- KV ladder ---------------------------------------------------------------


class TestKVLadder:
    def test_pow2_rungs_from_page(self):
        lad = build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "16",
                                   "HOROVOD_KV_MAX_CONTEXT": "256"})
        assert lad.rungs == (16, 32, 64, 128, 256)
        assert lad.page == 16

    def test_non_pow2_max_is_its_own_top_rung(self):
        lad = build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "16",
                                   "HOROVOD_KV_MAX_CONTEXT": "48"})
        assert lad.rungs == (16, 32, 48)

    def test_rung_for_and_oversize(self):
        lad = build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "8",
                                   "HOROVOD_KV_MAX_CONTEXT": "32"})
        assert [lad.rung_for(n) for n in (1, 8, 9, 17, 32)] == \
            [8, 8, 16, 32, 32]
        with pytest.raises(ValueError):
            lad.rung_for(33)

    def test_digest_is_canonical_string(self):
        lad = build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "16",
                                   "HOROVOD_KV_MAX_CONTEXT": "64"})
        assert lad.digest == "kv-ladder-v1|page=16|r=16,32,64"

    def test_bad_knobs_raise(self):
        with pytest.raises(ValueError):
            build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "0",
                                 "HOROVOD_KV_MAX_CONTEXT": "64"})
        with pytest.raises(ValueError):
            build_kv_ladder(env={"HOROVOD_KV_PAGE_TOKENS": "32",
                                 "HOROVOD_KV_MAX_CONTEXT": "16"})


def test_all_decode_knobs_declared():
    """Every HOROVOD_SERVING_DECODE_* / HOROVOD_KV_* tunable is a
    declared knob (the HVD002 registry/docs-drift gate hangs off
    this list)."""
    declared = {k.env: k for k in config.KNOBS}
    expected = {
        "HOROVOD_SERVING_DECODE_SLOTS": 4,
        "HOROVOD_SERVING_DECODE_MAX_NEW_TOKENS": 64,
        "HOROVOD_SERVING_DECODE_WATERMARK_STRIDE": 8,
        "HOROVOD_SERVING_DECODE_INTERACTIVE_SLO_MS": 250.0,
        "HOROVOD_SERVING_DECODE_LANE_BUDGET": 0.5,
        "HOROVOD_SERVING_DECODE_RETRY_LIMIT": 3,
        "HOROVOD_SERVING_DECODE_RETRY_BACKOFF_MS": 25.0,
        "HOROVOD_SERVING_DECODE_LEASE_TIMEOUT_S": 10.0,
        "HOROVOD_SERVING_DECODE_EMIT_STRIDE": 1,
        "HOROVOD_KV_PAGE_TOKENS": 16,
        "HOROVOD_KV_MAX_CONTEXT": 256,
    }
    for name, default in expected.items():
        assert name in declared, name
        assert declared[name].default == default, name


# -- the exactly-once token latch --------------------------------------------


class TestSequenceLatch:
    def _seq(self, slo_ms=None):
        return SequenceFuture(0, [1, 2], max_new=8, seed=0,
                              slo_ms=slo_ms, interactive_ms=250.0)

    def test_in_order_emission_accepted(self):
        s = self._seq()
        assert s.emit(0, 5, epoch=0)
        assert s.emit(1, 6, epoch=0)
        assert s.tokens == [5, 6]

    def test_duplicate_index_rejected(self):
        s = self._seq()
        assert s.emit(0, 5, epoch=0)
        assert not s.emit(0, 5, epoch=0)   # exact duplicate
        assert not s.emit(0, 9, epoch=0)   # conflicting duplicate
        assert s.tokens == [5]

    def test_out_of_order_rejected(self):
        s = self._seq()
        assert not s.emit(1, 5, epoch=0)
        assert s.tokens == []

    def test_stale_epoch_rejected(self):
        """The revenant path: a lease revoked by re-admission or shed
        cannot emit — its epoch no longer matches."""
        s = self._seq()
        assert s.emit(0, 5, epoch=0)
        new_epoch, frontier = s.advance_epoch()
        assert (new_epoch, frontier) == (1, 1)
        assert not s.emit(1, 6, epoch=0)    # revenant
        assert s.emit(1, 6, epoch=1)        # rightful owner
        assert s.tokens == [5, 6]

    def test_finish_latches_exactly_once(self):
        s = self._seq()
        assert s.finish("ok", epoch=0)
        assert not s.finish("ok", epoch=0)       # duplicate completion
        assert not s.finish("failed", epoch=0)   # conflicting dup
        assert not s.emit(0, 5, epoch=0)         # post-completion emit
        assert list(s.result(timeout=1)) == []

    def test_stale_epoch_finish_rejected(self):
        s = self._seq()
        s.advance_epoch()
        assert not s.finish("ok", epoch=0)
        assert s.finish("ok", epoch=1)

    def test_lane_classification(self):
        assert self._seq(slo_ms=100.0).lane == "interactive"
        assert self._seq(slo_ms=1000.0).lane == "batch"
        s = self._seq(slo_ms=None)
        assert s.lane == "batch" and s.slo_class == "default"


# -- engine: compile pin, rung growth, re-prefill equivalence -----------------


class TestEngine:
    def _engine(self, env=None, **kw):
        return DecodeEngine(env=env or _env(), **kw)

    def _run(self, eng, spec):
        emits, finishes = [], []
        eng.admit(spec)
        while eng.active:
            e, f = eng.step()
            emits += e
            finishes += f
        return emits, finishes

    def test_compile_count_flat_past_warmup(self):
        """Cache growth across every rung never recompiles: the
        compile count is pinned to len(rungs) by AOT warmup."""
        eng = self._engine()
        eng.warmup()
        assert eng.compiles == len(eng.ladder.rungs)
        # 3-token prompt + 50 new tokens crosses rungs 8->16->32->64
        spec = _SeqSpec(0, (1, 2, 3), (), seed=1, max_new=50,
                        epoch=0, lane="batch")
        emits, finishes = self._run(eng, spec)
        assert len(emits) == 50
        assert finishes[0][1] == "ok"
        assert eng.compiles == len(eng.ladder.rungs)

    def test_truncated_at_max_context(self):
        eng = self._engine()
        eng.warmup()
        spec = _SeqSpec(0, tuple(range(1, 60)), (), seed=1,
                        max_new=50, epoch=0, lane="batch")
        emits, finishes = self._run(eng, spec)
        assert finishes[0][1] == "truncated"
        assert len(emits) == 64 - 59

    def test_reprefill_equivalence_bitwise(self):
        """The watermark-resume contract: re-prefilling the prompt
        plus the delivered tokens reproduces the interrupted decode
        BITWISE — same tokens and same logits at the same seed — and
        the replay region emits nothing."""
        env = _env()
        prompt, k, total = (3, 1, 4), 7, 20
        eng = self._engine(env=env, capture_logits=True)
        eng.warmup()
        spec = _SeqSpec(0, prompt, (), seed=11, max_new=total,
                        epoch=0, lane="batch")
        emits, _ = self._run(eng, spec)
        tokens = [t for _, _, t, _ in emits]
        logits = {g: row for _, g, _, row in emits}
        assert len(tokens) == total

        eng2 = self._engine(env=env, capture_logits=True)
        eng2.warmup()
        spec2 = _SeqSpec(0, prompt, tuple(tokens[:k]), seed=11,
                         max_new=total, epoch=1, lane="batch")
        emits2, finishes2 = self._run(eng2, spec2)
        # zero re-emitted tokens: the replay region is silent
        assert min(g for _, g, _, _ in emits2) == k
        assert [t for _, _, t, _ in emits2] == tokens[k:]
        for _, g, _, row in emits2:
            assert np.array_equal(row, logits[g]), g
        assert finishes2[0][1] == "ok"

    def test_neighbor_slots_cannot_change_results(self):
        """Slots are independent: the same sequence decodes to the
        same tokens whether it runs alone or beside others."""
        env = _env()
        eng = self._engine(env=env)
        eng.warmup()
        solo, _ = self._run(
            eng, _SeqSpec(0, (5, 6), (), 3, 12, 0, "batch"))
        eng2 = self._engine(env=env)
        eng2.warmup()
        eng2.admit(_SeqSpec(1, (9, 9, 9), (), 4, 12, 0, "batch"))
        eng2.admit(_SeqSpec(2, (5, 6), (), 3, 12, 0, "batch"))
        eng2.admit(_SeqSpec(3, (7,), (), 5, 12, 0, "batch"))
        emits = []
        while eng2.active:
            e, _ = eng2.step()
            emits += e
        packed = [t for s, _, t, _ in emits if s.sid == 2]
        assert packed == [t for _, _, t, _ in solo]


# -- local frontend -----------------------------------------------------------


class TestFrontendLocal:
    def test_round_trip_and_determinism(self, tmp_path):
        env = _env(tmp_path)
        fe = DecodeFrontend(workers=2, env=env, trace_tag="rt")
        try:
            futs = [fe.submit([1, 2, 3], max_new_tokens=12, seed=s)
                    for s in range(5)]
            outs = _drain(fe, futs)
            assert all(len(o) == 12 for o in outs)
            again = fe.submit([1, 2, 3], max_new_tokens=12,
                              seed=0).result(timeout=60)
            assert list(again) == outs[0]
            st = fe.stats()
            assert st["completed"] == 6 and st["failed"] == 0
            assert st["dupes"] == 0
        finally:
            fe.close()

    def test_watermark_monotone_in_journal(self, tmp_path):
        env = _env(tmp_path)   # stride 4
        fe = DecodeFrontend(workers=1, env=env, trace_tag="wm")
        try:
            f = fe.submit([1, 2], max_new_tokens=16, seed=2)
            f.result(timeout=60)
        finally:
            fe.close()
        evs = _journal_events(tmp_path, "serving-wm")
        marks = [e["token"] for e in evs
                 if e["type"] == "seq_watermark" and e["sid"] == f.id]
        assert marks == [3, 7, 11, 15]      # stride multiples, in order
        assert marks == sorted(marks)
        done = [e for e in evs if e["type"] == "seq_done"]
        assert done and done[0]["tokens"] == 16
        assert done[0]["outcome"] == "ok"

    def test_fault_error_resumes_from_watermark(self, tmp_path):
        """A worker killed mid-sequence by the decode.step seam: its
        sequences resume on the survivor and the delivered stream
        bitwise-matches an uninterrupted run — zero dropped, zero
        re-emitted."""
        env = _env(tmp_path)
        fe = DecodeFrontend(workers=1, env=env, trace_tag="base")
        try:
            base = [list(fe.submit([1, 2, 3], max_new_tokens=40,
                                   seed=s).result(timeout=120))
                    for s in range(2)]
        finally:
            fe.close()

        faults.configure("decode.step:error:at=12", seed=0)
        fe2 = DecodeFrontend(workers=2, env=env, trace_tag="kill")
        fe2.start_watchdog()
        try:
            futs = [fe2.submit([1, 2, 3], max_new_tokens=40, seed=s)
                    for s in range(2)]
            outs = _drain(fe2, futs)
            assert outs == base
            st = fe2.stats()
            assert st["resumed"] >= 1
            assert st["dupes"] == 0
            assert st["completed"] == 2 and st["failed"] == 0
        finally:
            fe2.close()
        evs = _journal_events(tmp_path, "serving-kill")
        resumed = [e for e in evs if e["type"] == "seq_resumed"]
        assert resumed and resumed[0]["cause"] == "fault_error"
        assert resumed[0]["from_token"] >= resumed[0]["watermark"]

    def test_retry_limit_exhausted_fails_visibly(self, tmp_path):
        env = _env(tmp_path, HOROVOD_SERVING_DECODE_RETRY_LIMIT="0")
        faults.configure("decode.step:error:at=5", seed=0)
        fe = DecodeFrontend(workers=1, env=env, trace_tag="exhaust")
        try:
            futs = [fe.submit([1, 2, 3], max_new_tokens=30, seed=s)
                    for s in range(2)]
            failed = 0
            for f in futs:
                with pytest.raises(DecodeError):
                    f.result(timeout=60)
                failed += 1
            assert failed == 2
            assert fe.stats()["failed"] == 2
        finally:
            fe.close()
        evs = _journal_events(tmp_path, "serving-exhaust")
        assert [e for e in evs if e["type"] == "seq_failed"]

    def test_batch_lane_sheds_for_interactive(self, tmp_path):
        """Graceful degradation: with the pool full of batch work and
        an interactive sequence waiting, the least-progressed batch
        sequence is parked (and later finishes) while the interactive
        lane gets its slot."""
        env = _env(tmp_path, HOROVOD_SERVING_DECODE_SLOTS="2",
                   HOROVOD_SERVING_DECODE_LANE_BUDGET="0.5")
        # The toy LM steps in microseconds — slow every decode step
        # so the batch sequences are genuinely long-running when the
        # interactive one arrives.
        faults.configure("decode.step:delay:ms=15,every=1", seed=0)
        fe = DecodeFrontend(workers=1, env=env, trace_tag="shed")
        try:
            heavy = [fe.submit([1, 2, 3], max_new_tokens=50, seed=s,
                               slo_ms=10000.0) for s in range(2)]
            eng = fe._threads["w0"].engine
            deadline = time.monotonic() + 30
            while (eng.active_by_lane().get("batch", 0) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)   # wait out AOT warmup + admission
            assert eng.active_by_lane()["batch"] == 2
            quick = fe.submit([4, 5], max_new_tokens=8, seed=9,
                              slo_ms=50.0)
            assert quick.lane == "interactive"
            assert len(quick.result(timeout=60)) == 8
            outs = _drain(fe, heavy)
            assert all(len(o) == 50 for o in outs)
            st = fe.stats()
            assert st["shed"] >= 1
            assert st["completed"] == 3 and st["failed"] == 0
            assert st["dupes"] == 0
        finally:
            fe.close()
        evs = _journal_events(tmp_path, "serving-shed")
        sheds = [e for e in evs if e["type"] == "seq_shed"]
        assert sheds and sheds[0]["lane"] == "batch"

    def test_admission_steals_from_longest_queue(self, tmp_path):
        """Sharded admission: a worker with an empty queue steals
        from another worker's backlog instead of idling."""
        env = _env(tmp_path, HOROVOD_SERVING_DECODE_SLOTS="2")
        fe = DecodeFrontend(workers=1, env=env, trace_tag="steal")
        try:
            futs = [fe.submit([1, 2], max_new_tokens=20, seed=s)
                    for s in range(6)]   # all queue on w0
            fe.add_worker("w9")          # empty queue: must steal
            _drain(fe, futs)
            assert fe.stats()["steals"] >= 1
        finally:
            fe.close()

    def test_close_fails_stragglers_visibly(self, tmp_path):
        env = _env(tmp_path)
        fe = DecodeFrontend(workers=1, env=env, trace_tag="close")
        f = fe.submit([1, 2], max_new_tokens=1000, seed=0)
        fe.close()
        with pytest.raises(DecodeError):
            f.result(timeout=10)

    def test_submit_validates_prompt(self, tmp_path):
        env = _env(tmp_path)
        fe = DecodeFrontend(workers=0, env=env, trace_tag="val")
        try:
            with pytest.raises(ValueError):
                fe.submit([], max_new_tokens=4)
            with pytest.raises(ValueError):
                fe.submit(list(range(64)), max_new_tokens=4)
        finally:
            fe.close()


# -- the real-process mid-sequence kill ---------------------------------------


class TestRemoteKill:
    def _spawn(self, port, secret, wid, extra_env):
        env = dict(os.environ)
        env.update(extra_env)
        env.update({
            "DECODE_TEST_ADDR": "127.0.0.1",
            "DECODE_TEST_PORT": str(port),
            "DECODE_TEST_SECRET": secret,
            "DECODE_TEST_WID": wid,
            "JAX_PLATFORMS": "cpu",
        })
        return subprocess.Popen(
            [sys.executable, CHAOS_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)

    def test_real_worker_kill_mid_sequence(self, tmp_path):
        """The headline: a REAL process crash (exit 43) mid-sequence.
        Every in-flight sequence resumes from its KV watermark on the
        survivor — zero dropped sequences, zero re-emitted tokens,
        streams bitwise-identical to an uninterrupted run."""
        env = _env(tmp_path,
                   HOROVOD_SERVING_DECODE_LEASE_TIMEOUT_S="1.0")
        fe = DecodeFrontend(workers=1, env=env, trace_tag="killbase")
        try:
            base = [list(fe.submit([1, 2, 3], max_new_tokens=24,
                                   seed=s).result(timeout=120))
                    for s in range(3)]
        finally:
            fe.close()

        fe2 = DecodeFrontend(workers=0, env=env, trace_tag="killrun")
        fe2.start_watchdog()
        port, secret = fe2.decode_endpoint()
        worker_env = {k: str(v) for k, v in env.items()}
        crashy = self._spawn(
            port, secret, "crashy",
            dict(worker_env, HOROVOD_FAULTS="decode.step:crash:at=15",
                 HOROVOD_FAULTS_SEED="0"))
        try:
            futs = [fe2.submit([1, 2, 3], max_new_tokens=24, seed=s)
                    for s in range(3)]
            rc = crashy.wait(timeout=180)
            assert rc == faults.CRASH_EXIT_CODE
            survivor = self._spawn(port, secret, "survivor",
                                   dict(worker_env))
            try:
                outs = _drain(fe2, futs, timeout=180)
                # zero dropped: every sequence completed...
                assert [f.outcome for f in futs] == ["ok"] * 3
                # ...zero re-emitted: streams match uninterrupted runs
                assert outs == base
                st = fe2.stats()
                assert st["resumed"] >= 1
                assert st["dupes"] == 0 and st["failed"] == 0
            finally:
                fe2.close()
                survivor.wait(timeout=60)
        finally:
            if crashy.poll() is None:
                crashy.kill()
        evs = _journal_events(tmp_path, "serving-killrun")
        resumed = [e for e in evs if e["type"] == "seq_resumed"]
        assert resumed
        assert all(e["from_token"] >= max(0, e["watermark"])
                   for e in resumed)


# -- doctor serve: decode lanes ------------------------------------------------


class TestServingTraceDecode:
    def _record_leg(self, tmp_path, workers, tag, fault=None):
        env = _env(tmp_path,
                   HOROVOD_KV_MAX_CONTEXT="32")
        if fault:
            faults.configure(fault, seed=0)
        fe = DecodeFrontend(workers=workers, env=env, trace_tag=tag)
        fe.start_watchdog()
        try:
            futs = [fe.submit([1, 2, 3], max_new_tokens=12, seed=s,
                              slo_ms=(50.0 if s % 2 else 5000.0))
                    for s in range(6)]
            _drain(fe, futs)
        finally:
            fe.close()
            faults.configure("", seed=0)
            if journal._journal is not None:
                journal._journal.close()
            journal._journal = None

    def test_decode_only_journal_reports(self, tmp_path):
        from horovod_tpu import serving_trace
        self._record_leg(tmp_path, 1, "d1")
        self._record_leg(tmp_path, 2, "d2",
                         fault="decode.step:error:at=25")
        jdir = os.path.join(str(tmp_path), "journal")
        report = serving_trace.serving_report(jdir)
        legs = {l["role"]: l for l in report["legs"]}
        d1 = legs["serving-d1"]["decode"]
        d2 = legs["serving-d2"]["decode"]
        assert d1["sequences"] == 6 and d1["tokens"] == 72
        assert d1["meta_workers"] == 1 and d2["meta_workers"] == 2
        assert set(d1["lanes"]) == {"interactive", "batch"}
        assert d2["resume_spans"], "fault leg must carry resume spans"
        sp = d2["resume_spans"][0]
        assert sp["from_token"] >= sp["watermark"]
        assert "decode_attribution" in report
        attr = report["decode_attribution"]
        assert attr["base_leg"] == "serving-d1"
        assert attr["scaled_leg"] == "serving-d2"
        # the rendered summary mentions the decode lanes
        text = serving_trace.render_serving_report(report)
        assert "decode:" in text and "resume seq" in text

    def test_doctor_serve_exit_contract_decode_only(self, tmp_path):
        from horovod_tpu.runner import doctor
        self._record_leg(tmp_path, 1, "solo")
        jdir = os.path.join(str(tmp_path), "journal")
        assert doctor.main(["serve", jdir]) == 0
        assert os.path.exists(os.path.join(jdir,
                                           "serving_report.json"))

    def test_doctor_serve_empty_dir_still_fails(self, tmp_path):
        from horovod_tpu.runner import doctor
        empty = os.path.join(str(tmp_path), "empty")
        os.makedirs(empty)
        assert doctor.main(["serve", empty]) == 1

    def test_r16_artifact_regenerates_byte_identically(self):
        """The schema-extension pin: the decode blocks are additive,
        so the committed batch-plane artifact regenerates to the
        exact committed bytes — and carries no decode keys."""
        from horovod_tpu import serving_trace
        report = serving_trace.serving_report(R16_DIR)
        new = json.dumps(report, indent=1, sort_keys=True) + "\n"
        with open(R16_ARTIFACT) as f:
            committed = f.read()
        assert new == committed
        assert "decode_attribution" not in report
        assert all("decode" not in leg for leg in report["legs"])


class TestCommittedDecodeArtifacts:
    """The r18 acceptance pins: SERVING_ATTRIBUTION_r18.json
    regenerates byte-identically from the committed decode recording
    (benchmarks/serving_decode_r18/), the committed bench doc shows a
    monotone 1->2->4-worker tokens/s curve, and the chaos leg proves
    a real mid-sequence worker death resumed every in-flight sequence
    with zero dropped sequences and zero re-emitted tokens."""

    def test_r18_artifact_regenerates_byte_identically(self, tmp_path):
        from horovod_tpu import serving_trace
        out = os.path.join(str(tmp_path), "regen.json")
        serving_trace.write_serving_report(R18_DIR, out=out)
        with open(R18_ARTIFACT, "rb") as f:
            want = f.read()
        assert open(out, "rb").read() == want
        # the recording's in-dir report is the same bytes too
        assert open(os.path.join(R18_DIR, "serving_report.json"),
                    "rb").read() == want

    def test_r18_attribution_acceptance(self):
        report = json.load(open(R18_ARTIFACT))
        from horovod_tpu import serving_trace
        assert report["schema"] == serving_trace.REPORT_SCHEMA
        legs = {leg["role"]: leg for leg in report["legs"]}
        assert {"serving-d1", "serving-d2", "serving-dkill"} <= \
            set(legs)
        for role in ("serving-d1", "serving-d2", "serving-dkill"):
            assert "decode" in legs[role]
        attr = report["decode_attribution"]
        assert attr["base_leg"] == "serving-d1"
        assert attr["scaled_leg"] == "serving-d2"
        # the r16 lesson applied: admission must not pay for the
        # second worker on the decode plane
        assert attr["dominant_phase"] != "admission"
        assert attr["admission_share_scaled"] < \
            attr["admission_share_base"]
        # the chaos leg's resume spans are in the committed report
        kill = legs["serving-dkill"]["decode"]
        assert kill["resumed_sequences"] >= 1
        assert kill["failed_sequences"] == 0
        assert all(s["from_token"] >= 0
                   for s in kill["resume_spans"])

    def test_r18_bench_doc_pins(self):
        doc = json.load(open(R18_BENCH))
        t1 = doc["scaleout"]["workers1"]["tokens_per_s"]
        t2 = doc["scaleout"]["workers2"]["tokens_per_s"]
        t4 = doc["scaleout"]["workers4"]["tokens_per_s"]
        assert t1 < t2 < t4  # the r15 regression is gone
        chaos = doc["chaos"]
        assert chaos["worker_exit_code"] == 43
        assert chaos["dropped"] == 0
        assert chaos["failed"] == 0
        assert chaos["resumed"] >= 1
        assert chaos["duplicate_tokens_suppressed"] == 0
        assert chaos["streams_match_uninterrupted_baseline"] is True
        attr = json.load(open(R18_ARTIFACT))["decode_attribution"]
        assert doc["decode_attribution"]["admission_share_scaled"] \
            == attr["admission_share_scaled"]

    def test_r18_trajectory_row(self):
        traj = json.load(open(TRAJECTORY))
        row = traj["r18_decode"]
        doc = json.load(open(R18_BENCH))
        assert row["scaleout_4worker_tokens_per_s"] == \
            doc["scaleout"]["workers4"]["tokens_per_s"]
        assert row["chaos_dropped_sequences"] == 0
        assert row["chaos_streams_match_baseline"] is True
        attr = json.load(open(R18_ARTIFACT))["decode_attribution"]
        assert row["admission_share_base"] == \
            attr["admission_share_base"]
        assert row["admission_share_scaled"] == \
            attr["admission_share_scaled"]
        assert row["source"] == \
            "benchmarks/BENCH_serving_decode_r18.json + " \
            "benchmarks/SERVING_ATTRIBUTION_r18.json"
