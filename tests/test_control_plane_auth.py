"""Native control-plane authentication: the coordinator's TCP
listener only hands rank slots to peers presenting the job-derived
auth token (reference threat model: secret.py-authenticated launcher
RPCs, extended to the C++ negotiation plane — the reference's gloo
control plane is unauthenticated; this build closes that)."""

import socket
import struct

import pytest

from horovod_tpu.core import native
from horovod_tpu.ops.controller import control_plane_token
from horovod_tpu.runner.launch import free_port

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core not built")


def _hello_frame(rank: int, token: str) -> bytes:
    payload = struct.pack(">I", rank) + \
        struct.pack(">I", len(token)) + token.encode()
    return bytes([1]) + struct.pack(">I", len(payload)) + payload


def _mk_core(rank, size, port, token):
    return native.NativeCore(
        rank=rank, size=size, coord_host="127.0.0.1", coord_port=port,
        fusion_threshold=1024, cycle_time_ms=0.5, stall_warn_s=60.0,
        stall_kill_s=0.0, connect_timeout_s=10.0, cache_capacity=16,
        auth_token=token)


def test_forged_hello_rejected_and_slot_stays_free():
    port = free_port()
    c0 = _mk_core(0, 2, port, "sekrit-token")
    try:
        # Impostor: claims rank 1 with the wrong token. The
        # coordinator must close the connection AND leave the rank-1
        # slot unclaimed.
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            s.sendall(_hello_frame(1, "wrong-token"))
            s.settimeout(5)
            assert s.recv(1) == b""  # peer closed = rejected
        # The real rank 1 still gets the slot and negotiation works.
        c1 = _mk_core(1, 2, port, "sekrit-token")
        try:
            c0.submit("t", "f32|0|0|1.0|1.0#4", 16)
            c1.submit("t", "f32|0|0|1.0|1.0#4", 16)
            got0 = _drain(c0)
            got1 = _drain(c1)
            assert [e.name for e in got0] == ["t"]
            assert [e.name for e in got1] == ["t"]
        finally:
            c1.shutdown()
    finally:
        c0.shutdown()


def test_unauthenticated_mode_still_open():
    """No token configured (no job secret): hellos are accepted —
    single-user compatibility, matching secret.verify()'s semantics."""
    port = free_port()
    c0 = _mk_core(0, 2, port, "")
    try:
        c1 = _mk_core(1, 2, port, "anything")
        try:
            c0.submit("x", "f32|0|0|1.0|1.0#2", 8)
            c1.submit("x", "f32|0|0|1.0|1.0#2", 8)
            assert [e.name for e in _drain(c0)] == ["x"]
            assert [e.name for e in _drain(c1)] == ["x"]
        finally:
            c1.shutdown()
    finally:
        c0.shutdown()


def test_duplicate_rank_claim_cannot_disrupt():
    """A late hello for an already-claimed rank (full world: it stays
    unaccepted in the backlog; partial world: the claim-once check
    drops it) must not disturb negotiation between the real ranks."""
    port = free_port()
    c0 = _mk_core(0, 2, port, "tok")
    try:
        c1 = _mk_core(1, 2, port, "tok")
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(_hello_frame(1, "tok"))
                c0.submit("y", "f32|0|0|1.0|1.0#2", 8)
                c1.submit("y", "f32|0|0|1.0|1.0#2", 8)
                assert [e.name for e in _drain(c0)] == ["y"]
                assert [e.name for e in _drain(c1)] == ["y"]
        finally:
            c1.shutdown()
    finally:
        c0.shutdown()


def test_token_derivation(monkeypatch):
    from horovod_tpu.runner import secret as S
    monkeypatch.delenv(S.ENV_VAR, raising=False)
    assert control_plane_token() == ""
    monkeypatch.setenv(S.ENV_VAR, "k1")
    t1 = control_plane_token()
    monkeypatch.setenv(S.ENV_VAR, "k2")
    t2 = control_plane_token()
    assert t1 and t2 and t1 != t2 and len(t1) == 64


def _drain(core, max_wait=10.0):
    import time
    entries = []
    deadline = time.monotonic() + max_wait
    while not entries and time.monotonic() < deadline:
        batch = core.next_batch(0.5)
        if batch:
            entries.extend(batch)
    return entries
