"""Native control-plane authentication: mutual challenge-response
rank rendezvous. The coordinator challenges every connection with a
fresh nonce and hands out a rank slot only for a valid
HMAC-SHA256(secret, nonce|worker|rank); it then proves its own
possession of the secret over the worker's nonce. Replaying a
captured handshake is useless (fresh nonce per connection).
Reference contrast: the gloo control plane is unauthenticated — this
build extends the secret.py threat model down into the C++ core
(core/cc/sha256.h)."""

import hashlib
import hmac as hmac_mod
import socket
import struct

import pytest

from horovod_tpu.core import native
from horovod_tpu.ops.controller import control_plane_secret
from horovod_tpu.runner.launch import free_port

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core not built")


def _recv_frame(s):
    hdr = b""
    while len(hdr) < 5:
        b = s.recv(5 - len(hdr))
        assert b, "peer closed mid-frame"
        hdr += b
    t = hdr[0]
    (n,) = struct.unpack(">I", hdr[1:5])
    payload = b""
    while len(payload) < n:
        b = s.recv(n - len(payload))
        assert b, "peer closed mid-frame"
        payload += b
    return t, payload


def _send_frame(s, t, payload):
    s.sendall(bytes([t]) + struct.pack(">I", len(payload)) + payload)


def _get_str(buf, off):
    (n,) = struct.unpack(">I", buf[off:off + 4])
    return buf[off + 4:off + 4 + n], off + 4 + n


def _put_str(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def _worker_mac(secret: str, coord_nonce: bytes, rank: int) -> bytes:
    msg = coord_nonce + b"|worker|" + str(rank).encode()
    return hmac_mod.new(secret.encode(), msg, hashlib.sha256).digest()


def _handshake(s, secret: str, rank: int, mac_override: bytes = None):
    """Drive the worker side of the handshake by hand; returns the
    coordinator's welcome MAC payload (or None if it closed on us)."""
    t, payload = _recv_frame(s)
    assert t == 5, t  # kChallenge
    coord_nonce, _ = _get_str(payload, 0)
    mac = mac_override if mac_override is not None else \
        _worker_mac(secret, coord_nonce, rank)
    hello = struct.pack(">I", rank) + _put_str(b"wnonce-fixed") + \
        _put_str(mac)
    _send_frame(s, 1, hello)  # kHello
    try:
        s.settimeout(5)
        return _recv_frame(s)
    except AssertionError:
        return None


def _mk_core(rank, size, port, secret, connect_timeout=10.0):
    return native.NativeCore(
        rank=rank, size=size, coord_host="127.0.0.1", coord_port=port,
        fusion_threshold=1024, cycle_time_ms=0.5, stall_warn_s=60.0,
        stall_kill_s=0.0, connect_timeout_s=connect_timeout,
        cache_capacity=16, auth_secret=secret)


def _drain(core, max_wait=10.0):
    import time
    entries = []
    deadline = time.monotonic() + max_wait
    while not entries and time.monotonic() < deadline:
        batch = core.next_batch(0.5)
        if batch:
            entries.extend(batch)
    return entries


def test_wrong_mac_rejected_and_slot_stays_free():
    port = free_port()
    c0 = _mk_core(0, 2, port, "sekrit")
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            got = _handshake(s, "WRONG-secret", rank=1)
        assert got is None, "impostor with wrong secret got a welcome"
        # The real rank 1 still gets the slot and negotiation works.
        c1 = _mk_core(1, 2, port, "sekrit")
        try:
            c0.submit("t", "f32|0|0|1.0|1.0#4", 16)
            c1.submit("t", "f32|0|0|1.0|1.0#4", 16)
            assert [e.name for e in _drain(c0)] == ["t"]
            assert [e.name for e in _drain(c1)] == ["t"]
        finally:
            c1.shutdown()
    finally:
        c0.shutdown()


def test_replayed_mac_rejected():
    """A MAC captured from one handshake is useless on the next
    connection: the coordinator's nonce is fresh each time."""
    port = free_port()
    c0 = _mk_core(0, 3, port, "sekrit")
    try:
        # First connection: capture a VALID mac for rank 1 (we know
        # the secret here; a real attacker would have sniffed it).
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            t, payload = _recv_frame(s)
            nonce1, _ = _get_str(payload, 0)
            captured_mac = _worker_mac("sekrit", nonce1, 1)
            # abandon this handshake without completing it
        # Replay the captured mac on a NEW connection.
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            got = _handshake(s, "ignored", rank=1,
                             mac_override=captured_mac)
        assert got is None, "replayed MAC was accepted"
    finally:
        c0.shutdown()


def test_worker_rejects_unauthenticated_coordinator():
    """Mutual auth: a worker configured with a secret refuses a
    coordinator that cannot prove possession (here: a coordinator
    configured with NO secret sends an empty welcome MAC)."""
    port = free_port()
    c0 = _mk_core(0, 2, port, "")          # rogue/secretless coord
    try:
        with pytest.raises(RuntimeError,
                           match="coordinator failed authentication"):
            _mk_core(1, 2, port, "sekrit")
    finally:
        c0.shutdown()


def test_unauthenticated_mode_still_open():
    """No secret configured anywhere: handshake flows with empty MACs
    — single-user compatibility (secret.verify() semantics)."""
    port = free_port()
    c0 = _mk_core(0, 2, port, "")
    try:
        c1 = _mk_core(1, 2, port, "")
        try:
            c0.submit("x", "f32|0|0|1.0|1.0#2", 8)
            c1.submit("x", "f32|0|0|1.0|1.0#2", 8)
            assert [e.name for e in _drain(c0)] == ["x"]
            assert [e.name for e in _drain(c1)] == ["x"]
        finally:
            c1.shutdown()
    finally:
        c0.shutdown()


def test_secret_comes_from_env(monkeypatch):
    from horovod_tpu.runner import secret as S
    monkeypatch.delenv(S.ENV_VAR, raising=False)
    assert control_plane_secret() == ""
    monkeypatch.setenv(S.ENV_VAR, "k1")
    assert control_plane_secret() == "k1"


def test_silent_peer_cannot_block_rendezvous():
    """Slow-loris guard: a peer that connects and withholds its hello
    holds the serial accept loop only until the 10s ABSOLUTE handshake
    deadline (byte-dripping cannot reset it) — the real rank behind it
    still gets its slot and negotiation completes."""
    import threading
    port = free_port()
    c0 = _mk_core(0, 2, port, "tok")
    silent = socket.create_connection(("127.0.0.1", port), timeout=5)
    try:
        results = {}

        def join_late():
            # generous handshake deadline: the silent peer legally
            # holds the serial accept loop for up to its full 10s
            c1 = _mk_core(1, 2, port, "tok", connect_timeout=30.0)
            try:
                c0.submit("z", "f32|0|0|1.0|1.0#2", 8)
                c1.submit("z", "f32|0|0|1.0|1.0#2", 8)
                results["names"] = [e.name for e in _drain(c1, 30.0)]
            finally:
                c1.shutdown()

        t = threading.Thread(target=join_late, daemon=True)
        t.start()
        t.join(timeout=40.0)
        assert not t.is_alive(), "rendezvous blocked behind silent peer"
        assert results.get("names") == ["z"]
    finally:
        silent.close()
        c0.shutdown()


def test_oversized_preauth_frame_rejected():
    """An unauthenticated peer declaring a huge hello payload is cut
    off by the 4 KiB pre-auth cap — no large allocation, no slot."""
    port = free_port()
    c0 = _mk_core(0, 2, port, "tok")
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            _recv_frame(s)  # challenge
            s.sendall(bytes([1]) + struct.pack(">I", 1 << 30))
            s.settimeout(10)
            assert s.recv(1) == b""  # coordinator dropped us
    finally:
        c0.shutdown()
