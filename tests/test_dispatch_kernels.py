"""SPMD collective kernel math on an 8-device virtual mesh.

This is the single-process analog of the reference's 2-process Gloo
tests (test/parallel/test_torch.py): one process owns all 8 shards, so
every "rank"'s input and output can be constructed and checked exactly.
The same kernels run unmodified in true multi-process jobs (covered by
test_multiprocess.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops import dispatch
from horovod_tpu.ops.dispatch import (AVERAGE, SUM, MIN, MAX, PRODUCT)

N = 8


def make_global(mesh, per_rank_rows):
    """(n, *s) array sharded one row per device."""
    full = jnp.stack([jnp.asarray(r) for r in per_rank_rows])
    sharding = NamedSharding(mesh, P("proc"))
    return jax.device_put(full, sharding)


def rows_of(garr):
    return [np.asarray(s.data[0]) for s in
            sorted(garr.addressable_shards, key=lambda s: s.index[0].start)]


@pytest.mark.parametrize("op,expect", [
    (SUM, lambda xs: np.sum(xs, axis=0)),
    (AVERAGE, lambda xs: np.mean(xs, axis=0)),
    (MIN, lambda xs: np.min(xs, axis=0)),
    (MAX, lambda xs: np.max(xs, axis=0)),
    (PRODUCT, lambda xs: np.prod(xs, axis=0)),
])
def test_allreduce_ops(eight_device_mesh, op, expect):
    mesh = eight_device_mesh
    rng = np.random.RandomState(op)
    xs = rng.uniform(0.5, 1.5, size=(N, 3, 4)).astype(np.float32)
    kern = dispatch._allreduce_kernel(
        mesh, N, op, 1.0, 1.0, dispatch._sig([jnp.asarray(xs[0])]))
    (out,) = kern(make_global(mesh, xs))
    want = expect(xs)
    for got in rows_of(out):
        np.testing.assert_allclose(got, want, rtol=2e-5)


def test_allreduce_int_sum(eight_device_mesh):
    mesh = eight_device_mesh
    xs = np.arange(N * 4, dtype=np.int32).reshape(N, 4)
    kern = dispatch._allreduce_kernel(
        mesh, N, SUM, 1.0, 1.0, dispatch._sig([jnp.asarray(xs[0])]))
    (out,) = kern(make_global(mesh, xs))
    for got in rows_of(out):
        np.testing.assert_array_equal(got, xs.sum(0))


def test_allreduce_prescale_postscale(eight_device_mesh):
    mesh = eight_device_mesh
    xs = np.ones((N, 5), np.float32)
    kern = dispatch._allreduce_kernel(
        mesh, N, SUM, 0.5, 3.0, dispatch._sig([jnp.asarray(xs[0])]))
    (out,) = kern(make_global(mesh, xs))
    for got in rows_of(out):
        np.testing.assert_allclose(got, 0.5 * N * 3.0 * np.ones(5))


def test_fused_group_allreduce(eight_device_mesh):
    mesh = eight_device_mesh
    rng = np.random.RandomState(1)
    a = rng.randn(N, 3).astype(np.float32)
    b = rng.randn(N, 2, 2).astype(np.float32)
    sig = dispatch._sig([jnp.asarray(a[0]), jnp.asarray(b[0])])
    kern = dispatch._allreduce_kernel(mesh, N, SUM, 1.0, 1.0, sig)
    out_a, out_b = kern(make_global(mesh, a), make_global(mesh, b))
    for got in rows_of(out_a):
        np.testing.assert_allclose(got, a.sum(0), rtol=1e-5)
    for got in rows_of(out_b):
        np.testing.assert_allclose(got, b.sum(0), rtol=1e-5)


def test_broadcast_kernel(eight_device_mesh):
    # Single-tensor broadcast is a group of one (dispatch.broadcast
    # routes through the group kernel so it shares the wide path).
    mesh = eight_device_mesh
    xs = np.stack([np.full((3,), i, np.float32) for i in range(N)])
    for root in (0, 3, 7):
        kern = dispatch._broadcast_group_kernel(
            mesh, N, root, dispatch._sig([jnp.asarray(xs[0])]))
        (out,) = kern(make_global(mesh, xs))
        for got in rows_of(out):
            np.testing.assert_array_equal(got, xs[root])


def test_broadcast_group_kernel(eight_device_mesh):
    mesh = eight_device_mesh
    rng = np.random.RandomState(2)
    a = rng.randn(N, 3).astype(np.float32)
    b = rng.randn(N, 4).astype(np.float32)
    sig = dispatch._sig([jnp.asarray(a[0]), jnp.asarray(b[0])])
    kern = dispatch._broadcast_group_kernel(mesh, N, 2, sig)
    out_a, out_b = kern(make_global(mesh, a), make_global(mesh, b))
    for got in rows_of(out_a):
        np.testing.assert_allclose(got, a[2])
    for got in rows_of(out_b):
        np.testing.assert_allclose(got, b[2])


def test_allgather_even(eight_device_mesh):
    mesh = eight_device_mesh
    xs = np.stack([np.full((2, 3), i, np.float32) for i in range(N)])
    sizes = tuple([2] * N)
    kern = dispatch._allgather_kernel(
        mesh, N, sizes, dispatch._sig([jnp.asarray(xs[0])]))
    out = kern(make_global(mesh, xs))
    want = xs.reshape(N * 2, 3)
    for got in rows_of(out):
        np.testing.assert_array_equal(got, want)


def test_allgather_uneven(eight_device_mesh):
    mesh = eight_device_mesh
    # rank i contributes i+1 rows, padded to 8.
    sizes = tuple(i + 1 for i in range(N))
    maxr = max(sizes)
    padded = []
    pieces = []
    for i in range(N):
        block = np.full((sizes[i], 2), i, np.float32)
        pieces.append(block)
        pad = np.zeros((maxr - sizes[i], 2), np.float32)
        padded.append(np.concatenate([block, pad]))
    xs = np.stack(padded)
    kern = dispatch._allgather_kernel(
        mesh, N, sizes, dispatch._sig([jnp.asarray(xs[0])]))
    out = kern(make_global(mesh, xs))
    want = np.concatenate(pieces)
    for got in rows_of(out):
        np.testing.assert_array_equal(got, want)


def test_alltoall_kernel(eight_device_mesh):
    mesh = eight_device_mesh
    maxsplit = 2
    # packed[i, j] = chunk rank i sends to rank j; value = 10*i + j
    packed = np.zeros((N, N, maxsplit, 1), np.float32)
    for i in range(N):
        for j in range(N):
            packed[i, j] = 10 * i + j
    kern = dispatch._alltoall_kernel(
        mesh, N, maxsplit, dispatch._sig([jnp.asarray(packed[0])]))
    out = kern(make_global(mesh, packed))
    got_rows = rows_of(out)   # rank j receives (N, maxsplit, 1)
    for j in range(N):
        for i in range(N):
            np.testing.assert_array_equal(
                got_rows[j][i], np.full((maxsplit, 1), 10 * i + j))


def test_ppermute_shift_kernel(eight_device_mesh):
    mesh = eight_device_mesh
    xs = np.stack([np.full((2, 1), float(i), np.float32)
                   for i in range(N)])
    for shift in (1, 3, 7):
        kern = dispatch._ppermute_shift_kernel(
            mesh, N, shift, dispatch._sig([jnp.asarray(xs[0])]))
        out = kern(make_global(mesh, xs))
        for j, got in enumerate(rows_of(out)):
            np.testing.assert_array_equal(
                got, np.full((2, 1), float((j - shift) % N)))


class TestAlltoallLaunchAwareHeuristic:
    """Auto mode weighs per-launch overhead against byte savings
    (round-3 verdict weak #3): a skewed matrix that saves bytes must
    still pick padded on a high-latency host, where n-1 extra
    launches dominate."""

    def teardown_method(self, _):
        dispatch.set_launch_profile(None, 4e10, 16)

    def test_skewed_high_latency_picks_padded(self):
        # 50 ms/launch (a tunnel-attached host), 8 ranks, heavy skew:
        # ragged saves ~7/8 of the bytes but pays 7 launches.
        dispatch.set_launch_profile(0.05, 4e10, 16)
        n = 8
        buckets = [1] * (n - 1)            # 1-row buckets per round
        assert not dispatch._choose_alltoall_path(
            n, buckets, padded_rows=n * 64, row_bytes=8)

    def test_skewed_low_latency_picks_ragged(self):
        # Near-zero launch cost: byte savings decide (the MoE case).
        dispatch.set_launch_profile(0.0, 4e10, 16)
        n = 8
        buckets = [1] * (n - 1)
        assert dispatch._choose_alltoall_path(
            n, buckets, padded_rows=n * 64, row_bytes=8)

    def test_round_cap_forces_padded_at_large_n(self):
        # Even with free launches, past the round cap auto refuses
        # the linear-launch schedule.
        dispatch.set_launch_profile(0.0, 4e10, 16)
        n = 64
        buckets = [1] * (n - 1)
        assert not dispatch._choose_alltoall_path(
            n, buckets, padded_rows=n * 4096, row_bytes=8)

    def test_big_payload_beats_latency(self):
        # Large rows: byte savings outweigh even a slow host.
        dispatch.set_launch_profile(0.05, 4e10, 16)
        n = 8
        buckets = [4096] * (n - 1)          # ~29k rows ragged
        padded = n * 1 << 20                # ~8M rows padded
        assert dispatch._choose_alltoall_path(
            n, buckets, padded_rows=padded, row_bytes=4096)


def test_ragged_round_buckets():
    mat = np.array([[5, 1, 0],
                    [0, 7, 2],
                    [3, 0, 9]])
    # r=1: max(mat[0][1], mat[1][2], mat[2][0]) = 3 -> pow2 bucket 4
    # r=2: max(mat[0][2], mat[1][0], mat[2][1]) = 0 -> no exchange
    assert dispatch._ragged_round_buckets(mat) == [4, 0]
    assert dispatch._pow2_bucket(0) == 0
    assert dispatch._pow2_bucket(1) == 1
    assert dispatch._pow2_bucket(8) == 8
    assert dispatch._pow2_bucket(9) == 16


def test_reducescatter_even(eight_device_mesh):
    mesh = eight_device_mesh
    rng = np.random.RandomState(3)
    xs = rng.randn(N, 16, 3).astype(np.float32)
    rows = tuple([2] * N)
    kern = dispatch._reducescatter_kernel(
        mesh, N, SUM, 1.0, 1.0, rows, dispatch._sig([jnp.asarray(xs[0])]))
    out = kern(make_global(mesh, xs))
    total = xs.sum(0)
    got_rows = rows_of(out)
    for i in range(N):
        np.testing.assert_allclose(got_rows[i], total[2 * i:2 * i + 2],
                                   rtol=1e-5)


def test_reducescatter_uneven(eight_device_mesh):
    mesh = eight_device_mesh
    rng = np.random.RandomState(4)
    d0 = 11  # 8 ranks: rows (2,2,2,1,1,1,1,1)
    xs = rng.randn(N, d0, 2).astype(np.float32)
    base, rem = divmod(d0, N)
    rows = tuple(base + (1 if i < rem else 0) for i in range(N))
    kern = dispatch._reducescatter_kernel(
        mesh, N, SUM, 1.0, 1.0, rows, dispatch._sig([jnp.asarray(xs[0])]))
    out = kern(make_global(mesh, xs))
    total = xs.sum(0)
    offsets = np.concatenate([[0], np.cumsum(rows)])
    got_rows = rows_of(out)
    maxr = max(rows)
    for i in range(N):
        want = total[offsets[i]:offsets[i] + rows[i]]
        np.testing.assert_allclose(got_rows[i][:rows[i]], want, rtol=1e-5)
        assert got_rows[i].shape[0] == maxr


def test_reducescatter_group_fused(eight_device_mesh):
    """Fused rs group: mixed shapes (even + uneven first dims) in one
    launch; each rank's trimmed block matches the per-tensor rule."""
    mesh = eight_device_mesh
    rng = np.random.RandomState(6)
    a = rng.randn(N, 16, 2).astype(np.float32)   # even: 2 rows each
    b = rng.randn(N, 11).astype(np.float32)      # uneven: (2,2,2,1,...)
    sig = dispatch._sig([jnp.asarray(a[0]), jnp.asarray(b[0])])
    rows = (dispatch.reducescatter_rows(16, N),
            dispatch.reducescatter_rows(11, N))
    kern = dispatch._reducescatter_group_kernel(
        mesh, N, SUM, 1.0, 1.0, rows, sig)
    out_a, out_b = kern(make_global(mesh, a), make_global(mesh, b))
    ta, tb = a.sum(0), b.sum(0)
    offs_a = np.concatenate([[0], np.cumsum(rows[0])])
    offs_b = np.concatenate([[0], np.cumsum(rows[1])])
    for i, (ga, gb) in enumerate(zip(rows_of(out_a), rows_of(out_b))):
        np.testing.assert_allclose(
            ga[:rows[0][i]], ta[offs_a[i]:offs_a[i] + rows[0][i]],
            rtol=1e-5)
        np.testing.assert_allclose(
            gb[:rows[1][i]], tb[offs_b[i]:offs_b[i] + rows[1][i]],
            rtol=1e-5)


def test_adasum_kernel_matches_numpy(eight_device_mesh):
    from horovod_tpu.ops.adasum import _adasum_kernel, adasum_reference
    mesh = eight_device_mesh
    rng = np.random.RandomState(5)
    xs = rng.randn(N, 32).astype(np.float32)
    sig = dispatch._sig([jnp.asarray(xs[0])])
    kern = _adasum_kernel(mesh, N, sig)
    (out,) = kern(make_global(mesh, xs))
    want = adasum_reference([xs[i] for i in range(N)])
    for got in rows_of(out):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestAdasumVHDD:
    """The scalable halving-doubling schedule (reference: adasum.h
    DispatchFusedAllreduce) must match both the numpy oracle and the
    gather+fold kernel, and its per-rank wire must not scale with n."""

    def submesh(self, mesh, n):
        from jax.sharding import Mesh
        return Mesh(mesh.devices.flat[:n], axis_names=("proc",))

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_oracle_and_fold(self, eight_device_mesh, n):
        from horovod_tpu.ops.adasum import (_adasum_kernel,
                                            _adasum_kernel_vhdd,
                                            adasum_reference)
        mesh = self.submesh(eight_device_mesh, n)
        rng = np.random.RandomState(7 + n)
        xs = rng.randn(n, 37).astype(np.float32)  # odd length: pads
        sig = dispatch._sig([jnp.asarray(xs[0])])
        (out_v,) = _adasum_kernel_vhdd(mesh, n, sig)(
            make_global(mesh, xs))
        (out_g,) = _adasum_kernel(mesh, n, sig)(make_global(mesh, xs))
        want = adasum_reference([xs[i] for i in range(n)])
        got_v = [np.asarray(s.data[0]) for s in sorted(
            out_v.addressable_shards, key=lambda s: s.index[0].start)]
        got_g = [np.asarray(s.data[0]) for s in sorted(
            out_g.addressable_shards, key=lambda s: s.index[0].start)]
        for gv, gg in zip(got_v, got_g):
            np.testing.assert_allclose(gv, want, rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gv, gg, rtol=1e-4, atol=1e-5)

    def test_grouped_tensors(self, eight_device_mesh):
        from horovod_tpu.ops.adasum import (_adasum_kernel_vhdd,
                                            adasum_reference)
        n = 4
        mesh = self.submesh(eight_device_mesh, n)
        rng = np.random.RandomState(11)
        a = rng.randn(n, 5).astype(np.float32)
        b = rng.randn(n, 3, 2).astype(np.float32)
        sig = dispatch._sig([jnp.asarray(a[0]), jnp.asarray(b[0])])
        out_a, out_b = _adasum_kernel_vhdd(mesh, n, sig)(
            make_global(mesh, a), make_global(mesh, b))
        # fused: the fold runs over the CONCATENATED bucket
        flat = [np.concatenate([a[i].ravel(), b[i].ravel()])
                for i in range(n)]
        want = adasum_reference(flat)
        got_a = np.asarray(out_a.addressable_shards[0].data[0])
        got_b = np.asarray(out_b.addressable_shards[0].data[0])
        np.testing.assert_allclose(got_a, want[:5].reshape(5),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got_b, want[5:].reshape(3, 2),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("n", [3, 5, 6, 7])
    def test_non_pow2_matches_oracle(self, eight_device_mesh, n):
        """Non-power-of-two sets: pow2-block vhdd + right-to-left
        masked-psum merges must reproduce the fold tree exactly
        (round-4 verdict Missing #4; reference: adasum.h
        DispatchFusedAllreduce arbitrary group sizes)."""
        from horovod_tpu.ops.adasum import (_adasum_kernel,
                                            _adasum_kernel_vhdd,
                                            adasum_reference)
        mesh = self.submesh(eight_device_mesh, n)
        rng = np.random.RandomState(23 + n)
        xs = rng.randn(n, 53).astype(np.float32)  # odd length: pads
        sig = dispatch._sig([jnp.asarray(xs[0])])
        (out_v,) = _adasum_kernel_vhdd(mesh, n, sig)(
            make_global(mesh, xs))
        (out_g,) = _adasum_kernel(mesh, n, sig)(make_global(mesh, xs))
        want = adasum_reference([xs[i] for i in range(n)])
        got_v = [np.asarray(s.data[0]) for s in sorted(
            out_v.addressable_shards, key=lambda s: s.index[0].start)]
        got_g = [np.asarray(s.data[0]) for s in sorted(
            out_g.addressable_shards, key=lambda s: s.index[0].start)]
        assert len(got_v) == n
        for gv, gg in zip(got_v, got_g):
            np.testing.assert_allclose(gv, want, rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gv, gg, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("n", [5, 6])
    def test_non_pow2_wire_has_no_gather(self, eight_device_mesh, n):
        """The mixed schedule must stay gather-free: merges are
        masked psums (O(bucket) each), never an all_gather of the
        (n, total) stack."""
        from horovod_tpu.ops.adasum import _adasum_kernel_vhdd
        total = 4096
        mesh = self.submesh(eight_device_mesh, n)
        sig = dispatch._sig([jnp.zeros((total,), jnp.float32)])
        kern = _adasum_kernel_vhdd(mesh, n, sig)
        txt = kern.lower(
            jax.ShapeDtypeStruct((n, total), jnp.float32)).as_text()
        assert "all_gather" not in txt and "all-gather" not in txt

    @pytest.mark.parametrize("n", [4, 8])
    def test_wire_does_not_scale_with_n(self, eight_device_mesh, n):
        """Per-rank collective payloads are O(bucket), independent of
        n: no all-gather of the (n, total) stack anywhere in the
        program, and the largest collective message is bucket/2."""
        import re
        from horovod_tpu.ops.adasum import _adasum_kernel_vhdd
        total = 4096
        mesh = self.submesh(eight_device_mesh, n)
        sig = dispatch._sig([jnp.zeros((total,), jnp.float32)])
        kern = _adasum_kernel_vhdd(mesh, n, sig)
        txt = kern.lower(
            jax.ShapeDtypeStruct((n, total), jnp.float32)).as_text()
        assert "all_gather" not in txt and "all-gather" not in txt, \
            "vhdd must not gather the full contribution stack"
        # collective_permute payload widths: f32<K> operands
        sizes = [int(m) for m in re.findall(
            r"collective_permute.*?tensor<(\d+)xf32>", txt)]
        assert sizes, "expected ppermute exchanges in the program"
        assert max(sizes) <= total // 2, sizes


def test_adasum_orthogonal_is_sum():
    from horovod_tpu.ops.adasum import adasum_reference
    a = np.array([1.0, 0.0, 0.0])
    b = np.array([0.0, 1.0, 0.0])
    np.testing.assert_allclose(adasum_reference([a, b]), a + b)


def test_adasum_parallel_damps():
    from horovod_tpu.ops.adasum import adasum_reference
    a = np.array([1.0, 1.0])
    out = adasum_reference([a, a])
    # identical gradients: combine = a, not 2a
    np.testing.assert_allclose(out, a)


# --- hierarchical allreduce (reference: NCCLHierarchicalAllreduce,
# HOROVOD_HIERARCHICAL_ALLREDUCE) --------------------------------------


def make_hier_mesh():
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, axis_names=("cross", "local"))


@pytest.mark.parametrize("op", [SUM, AVERAGE])
@pytest.mark.parametrize("shape", [(3, 4), (5,), (7, 3)])
def test_hierarchical_matches_flat(eight_device_mesh, op, shape):
    """reduce-scatter(local) -> psum(cross) -> all-gather(local) must
    equal the flat single-phase psum on a 2x4 factoring of the same 8
    devices (including shapes that need padding to the local axis)."""
    mesh2 = make_hier_mesh()
    rng = np.random.RandomState(op + shape[0])
    xs = rng.uniform(-1, 1, size=(N,) + shape).astype(np.float32)
    sig = dispatch._sig([jnp.asarray(xs[0])])
    flat = dispatch._allreduce_kernel(
        eight_device_mesh, N, op, 1.0, 1.0, sig)
    hier = dispatch._allreduce_kernel_hier(mesh2, N, op, 1.0, 1.0, sig)
    (want,) = flat(make_global(eight_device_mesh, xs))
    g2 = jax.device_put(
        jnp.asarray(xs), NamedSharding(mesh2, P(("cross", "local"))))
    (got,) = hier(g2)
    # hierarchical reduction order differs from flat: float32
    # associativity noise needs an atol near zero
    np.testing.assert_allclose(
        np.asarray(jax.device_get(got)),
        np.asarray(jax.device_get(want)), rtol=2e-5, atol=2e-6)


def test_hierarchical_changes_lowered_program(eight_device_mesh):
    """The knob must change the compiled program: the hierarchical
    kernel lowers to reduce-scatter + all-gather phases, the flat one
    to a single all-reduce (VERDICT round-1 item 4 'assert on HLO')."""
    mesh2 = make_hier_mesh()
    xs = np.ones((N, 16), np.float32)
    sig = dispatch._sig([jnp.asarray(xs[0])])
    g1 = make_global(eight_device_mesh, xs)
    g2 = jax.device_put(
        jnp.asarray(xs), NamedSharding(mesh2, P(("cross", "local"))))
    flat_txt = dispatch._allreduce_kernel(
        eight_device_mesh, N, SUM, 1.0, 1.0, sig).lower(g1).as_text()
    hier_txt = dispatch._allreduce_kernel_hier(
        mesh2, N, SUM, 1.0, 1.0, sig).lower(g2).as_text()
    assert "reduce_scatter" in hier_txt
    assert "all_gather" in hier_txt
    assert "reduce_scatter" not in flat_txt


class TestHierWide:
    """Hierarchical staging composed with device spanning (round-4
    verdict Missing #2): on a ('cross','local','dev') factoring every
    chip carries 1/ndev of the bucket, and the DCN-crossing phase
    moves only 1/(local*dev) of the bytes."""

    def make_mesh(self):
        from jax.sharding import Mesh
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        return Mesh(devs, axis_names=("cross", "local", "dev"))

    @pytest.mark.parametrize("op", [SUM, AVERAGE])
    def test_matches_flat(self, eight_device_mesh, op):
        """The composed kernel must equal the flat psum on a 2x2x2
        factoring (4 simulated processes x 2 chips), including a
        bucket length needing the internal pad to 'local'."""
        mesh3 = self.make_mesh()
        n, ndev, k = 4, 2, 2051          # odd k: pads to L inside
        rng = np.random.RandomState(31 + op)
        xs = rng.uniform(-1, 1, size=(n, ndev * k)).astype(np.float32)
        sig = dispatch._sig([jnp.asarray(xs[0])])
        g = jax.device_put(
            jnp.asarray(xs.reshape(n, ndev, k)),
            NamedSharding(mesh3, P(("cross", "local"), "dev")))
        kern = dispatch._allreduce_kernel_hier_wide(
            mesh3, n, op, 1.0, 1.0, sig, None)
        (out,) = kern(g)
        want = xs.sum(0)
        if op == AVERAGE:
            want = want / n
        for s in out.addressable_shards:
            np.testing.assert_allclose(np.asarray(s.data[0]), want,
                                       rtol=2e-5, atol=2e-6)

    def test_wire_dtype_folds(self, eight_device_mesh):
        """fp16-wire compression folds into the composed program: the
        pack casts to the wire dtype, the kernel reduces on-wire and
        casts the output segment back to the raw dtype."""
        mesh3 = self.make_mesh()
        n, ndev, k = 4, 2, 2048
        rng = np.random.RandomState(41)
        xs = rng.uniform(-1, 1, size=(n, ndev * k)).astype(np.float32)
        sig = dispatch._sig([jnp.asarray(xs[0])])
        g = jax.device_put(
            jnp.asarray(xs.reshape(n, ndev, k).astype(np.float16)),
            NamedSharding(mesh3, P(("cross", "local"), "dev")))
        kern = dispatch._allreduce_kernel_hier_wide(
            mesh3, n, SUM, 1.0, 1.0, sig, "float16", ("float32",))
        (out,) = kern(g)
        got = np.asarray(out.addressable_shards[0].data[0])
        assert got.dtype == np.float32
        want = xs.astype(np.float16).sum(0)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_dcn_phase_moves_fraction(self):
        """HLO assertion (the r2 technique): the only all_reduce in
        the composed program is the cross-slice psum, and its payload
        is total/(local*dev) elements."""
        import re
        mesh3 = self.make_mesh()
        n, ndev, k = 4, 2, 2048
        total = ndev * k
        sig = dispatch._sig([jnp.zeros((total,), jnp.float32)])
        kern = dispatch._allreduce_kernel_hier_wide(
            mesh3, n, SUM, 1.0, 1.0, sig, None)
        txt = kern.lower(jax.ShapeDtypeStruct(
            (n, ndev, k), jnp.float32)).as_text()
        assert "reduce_scatter" in txt          # phase 1 (ICI)
        assert "all_gather" in txt              # phases 3 (ICI)
        assert txt.count("stablehlo.all_reduce") == 1
        # the all_reduce's type signature follows its reducer region
        m = re.search(r"all_reduce.*?tensor<(\d+)xf32>", txt, re.S)
        assert m, "expected the cross-slice psum in the program"
        assert int(m.group(1)) == total // (2 * ndev), m.group(0)[-80:]


def test_hier_mesh_alignment_rules():
    """Hierarchy only fires for slice-aligned contiguous rank sets."""
    aligned = dispatch._slice_aligned
    assert aligned([0, 1, 2, 3], 2)
    assert aligned(list(range(8)), 4)
    assert not aligned([1, 2, 4, 5], 2)   # group [1,2] not aligned
    assert not aligned([0, 1], 2)         # size == local_size
    assert not aligned([0, 2, 4, 6], 2)   # non-contiguous groups
    assert not aligned([0, 1, 2], 2)      # not divisible
    assert not aligned([0, 1, 2, 3], 0)   # disabled


def test_allgather_group_kernel_flat_and_hier(eight_device_mesh):
    """The fused allgather group (one launch for N uneven gathers)
    must reproduce each per-tensor gather, on both the flat 'proc'
    mesh and the hierarchical ('cross','local') staging."""
    mesh2 = make_hier_mesh()
    rows_a = (1, 4, 2, 3, 1, 2, 5, 2)
    rows_b = (2,) * N
    rng = np.random.RandomState(7)
    maxa, maxb = max(rows_a), max(rows_b)
    a = rng.randn(N, maxa, 3).astype(np.float32)
    b = rng.randn(N, maxb).astype(np.float32)
    want_a = np.concatenate([a[i, : rows_a[i]] for i in range(N)])
    want_b = np.concatenate([b[i, : rows_b[i]] for i in range(N)])
    sig = dispatch._sig([jnp.asarray(a[0]), jnp.asarray(b[0])])

    kern = dispatch._allgather_group_kernel(
        eight_device_mesh, N, (rows_a, rows_b), sig)
    out_a, out_b = kern(make_global(eight_device_mesh, a),
                        make_global(eight_device_mesh, b))
    for got in rows_of(out_a):
        np.testing.assert_allclose(got, want_a)
    for got in rows_of(out_b):
        np.testing.assert_allclose(got, want_b)

    hier = dispatch._allgather_group_kernel_hier(
        mesh2, N, (rows_a, rows_b), sig)
    spec = NamedSharding(mesh2, P(("cross", "local")))
    out_a, out_b = hier(jax.device_put(jnp.asarray(a), spec),
                        jax.device_put(jnp.asarray(b), spec))
    for got in rows_of(out_a):
        np.testing.assert_allclose(got, want_a)
    for got in rows_of(out_b):
        np.testing.assert_allclose(got, want_b)


@pytest.mark.parametrize("rows", [(3, 3, 3, 3, 3, 3, 3, 3),
                                  (1, 4, 2, 3, 1, 2, 5, 2)])
def test_hierarchical_allgather_matches_flat(eight_device_mesh, rows):
    """ICI gather-within-slice then DCN cross-slice exchange must
    reassemble the identical global-rank-ordered concat as the flat
    gather (reference: HOROVOD_HIERARCHICAL_ALLGATHER), including
    uneven per-rank first-dim sizes."""
    mesh2 = make_hier_mesh()
    maxr = max(rows)
    rng = np.random.RandomState(sum(rows))
    xs = rng.uniform(-1, 1, size=(N, maxr, 3)).astype(np.float32)
    sig = dispatch._sig([jnp.asarray(xs[0])])
    flat = dispatch._allgather_kernel(eight_device_mesh, N, rows, sig)
    hier = dispatch._allgather_kernel_hier(mesh2, N, rows, sig)
    want = flat(make_global(eight_device_mesh, xs))
    g2 = jax.device_put(
        jnp.asarray(xs), NamedSharding(mesh2, P(("cross", "local"))))
    got = hier(g2)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(got))[0],
        np.asarray(jax.device_get(want))[0])


def test_hierarchical_allgather_lowered_program(eight_device_mesh):
    """The hierarchical gather must lower to TWO all-gather phases
    (local then cross), not one fused gather over a flat axis."""
    mesh2 = make_hier_mesh()
    rows = (2,) * N
    xs = np.ones((N, 2, 4), np.float32)
    sig = dispatch._sig([jnp.asarray(xs[0])])
    g2 = jax.device_put(
        jnp.asarray(xs), NamedSharding(mesh2, P(("cross", "local"))))
    txt = dispatch._allgather_kernel_hier(
        mesh2, N, rows, sig).lower(g2).as_text()
    assert txt.count("all-gather") >= 2 or txt.count("all_gather") >= 2
