"""Parallelism-layer numerics: every strategy is checked against a
single-device oracle on the 8-virtual-device CPU mesh (SURVEY.md §4
technique 2 — fake devices instead of a cluster)."""

import jax
import jax.numpy as jnp
from horovod_tpu.common.compat import shard_map
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import (
    MeshSpec, attention, build_mesh, build_train_step, moe_ffn,
    pipeline_apply, ring_attention, stack_stage_params,
    ulysses_attention,
)
from horovod_tpu.parallel.mesh import data_parallel_mesh


def seq_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), axis_names=("seq",))


# ---------------------------------------------------------------------------
# MeshSpec
# ---------------------------------------------------------------------------

class TestMeshSpec:
    def test_auto_data(self):
        s = MeshSpec(tensor=2).resolve(8)
        assert s.data == 4 and s.tensor == 2 and s.total == 8

    def test_fixed_mismatch(self):
        with pytest.raises(ValueError):
            MeshSpec(data=3, tensor=2).resolve(8)

    def test_indivisible(self):
        with pytest.raises(ValueError):
            MeshSpec(tensor=3).resolve(8)

    def test_build_mesh_axes(self):
        m = build_mesh(MeshSpec(tensor=2, seq=2))
        assert m.shape["tensor"] == 2 and m.shape["seq"] == 2
        assert m.shape["data"] == 2
        m2 = build_mesh(MeshSpec(tensor=2), keep_trivial_axes=False)
        assert "seq" not in m2.shape and m2.shape["data"] == 4


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------

class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full(self, causal):
        B, L, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, L, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, L, H, D), jnp.float32)
        v = jax.random.normal(kv, (B, L, H, D), jnp.float32)

        oracle = attention(q, k, v, causal=causal)

        mesh = seq_mesh(4)
        ring = jax.jit(shard_map(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))
        out = ring(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_matches(self):
        B, L, H, D = 1, 16, 2, 8
        key = jax.random.PRNGKey(1)
        q, k, v = (jax.random.normal(kk, (B, L, H, D))
                   for kk in jax.random.split(key, 3))
        mesh = seq_mesh(4)

        def loss_ring(q, k, v):
            f = shard_map(
                lambda q, k, v: ring_attention(q, k, v, "seq"),
                mesh=mesh, in_specs=(P(None, "seq"),) * 3,
                out_specs=P(None, "seq"))
            return jnp.sum(f(q, k, v) ** 2)

        def loss_full(q, k, v):
            return jnp.sum(attention(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss_ring)(q, k, v)
        g2 = jax.grad(loss_full)(q, k, v)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)


class TestUlysses:
    def test_matches_full(self):
        B, L, H, D = 2, 32, 8, 16
        key = jax.random.PRNGKey(2)
        q, k, v = (jax.random.normal(kk, (B, L, H, D))
                   for kk in jax.random.split(key, 3))
        oracle = attention(q, k, v, causal=True)
        mesh = seq_mesh(4)
        f = jax.jit(shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "seq"),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))
        np.testing.assert_allclose(np.asarray(f(q, k, v)),
                                   np.asarray(oracle),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# MoE expert parallelism
# ---------------------------------------------------------------------------

class TestMoE:
    def test_ep_matches_single(self):
        T, Dm, E, F = 64, 16, 4, 32
        key = jax.random.PRNGKey(3)
        k1, k2, k3, k4 = jax.random.split(key, 4)
        tokens = jax.random.normal(k1, (T, Dm))
        router = jax.random.normal(k2, (Dm, E)) * 0.1
        w_in = jax.random.normal(k3, (E, Dm, F)) * 0.1
        w_out = jax.random.normal(k4, (E, F, Dm)) * 0.1

        out1, aux1 = moe_ffn(tokens, router, w_in, w_out,
                             capacity_factor=4.0, axis_name=None)

        ep = 2
        mesh = Mesh(np.array(jax.devices()[:ep]), axis_names=("expert",))
        # tokens replicated per-device would double T; instead shard
        # tokens over expert axis too (each device routes its half).
        f = jax.jit(shard_map(
            lambda t, r, wi, wo: moe_ffn(t, r, wi, wo,
                                         capacity_factor=4.0,
                                         axis_name="expert")[0],
            mesh=mesh,
            in_specs=(P("expert"), P(), P("expert"), P("expert")),
            out_specs=P("expert"),
        ))
        out2 = f(tokens, router, w_in, w_out)
        # Same routing decisions, different capacity bucketing: with
        # generous capacity, outputs must match.
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out1),
                                   rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------

class TestPipeline:
    def test_matches_sequential(self):
        S, Lps, D = 4, 2, 8      # 4 stages, 2 layers per stage
        n_micro, mb = 4, 4
        L = S * Lps
        key = jax.random.PRNGKey(4)
        w = jax.random.normal(key, (L, D, D)) * (1.0 / np.sqrt(D))
        x = jax.random.normal(jax.random.PRNGKey(5), (n_micro, mb, D))

        def layer(wi, h):
            return jnp.tanh(h @ wi)

        # oracle: sequential through all L layers
        def seq_apply(x):
            h = x
            for i in range(L):
                h = layer(w[i], h)
            return h
        oracle = jax.vmap(seq_apply)(x)

        mesh = Mesh(np.array(jax.devices()[:S]), axis_names=("pipe",))
        staged = stack_stage_params({"w": w}, S)["w"]  # (S, Lps, D, D)

        def stage_fn(pw, h):
            def body(h, wi):
                return layer(wi, h), None
            h, _ = lax.scan(body, h, pw)
            return h

        f = jax.jit(shard_map(
            # shard_map keeps the sharded leading dim (size 1): squeeze
            lambda pw, x: pipeline_apply(stage_fn, pw[0], x, "pipe"),
            mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P()))
        out = f(staged, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                                   rtol=1e-5, atol=1e-5)

    def test_pipeline_grads_flow(self):
        S, D = 2, 4
        mesh = Mesh(np.array(jax.devices()[:S]), axis_names=("pipe",))
        w = jax.random.normal(jax.random.PRNGKey(6), (S, 1, D, D)) * 0.5
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 3, D))

        def stage_fn(pw, h):
            return jnp.tanh(h @ pw[0])

        def loss(w):
            f = shard_map(
                lambda pw, x: pipeline_apply(stage_fn, pw[0], x, "pipe"),
                mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())
            return jnp.sum(f(w, x) ** 2)

        g = jax.grad(loss)(w)
        assert not np.allclose(np.asarray(g), 0.0)

        # oracle grads
        def loss2(w):
            h = x
            for s in range(S):
                h = stage_fn(w[s], h)
            return jnp.sum(h ** 2)
        g2 = jax.grad(loss2)(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g2),
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# DP train step
# ---------------------------------------------------------------------------

class TestTrainStep:
    def test_dp_matches_full_batch(self):
        import optax
        from horovod_tpu.models import init_mlp, mlp_loss_fn

        mesh = data_parallel_mesh()
        n = mesh.shape["data"]
        params = init_mlp(jax.random.PRNGKey(0), (16, 32, 4))
        opt = optax.sgd(0.1)
        opt_state = opt.init(params)

        B = 8 * n
        images = jax.random.normal(jax.random.PRNGKey(1), (B, 16))
        labels = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 4)
        batch = {"images": images, "labels": labels}

        step = build_train_step(mlp_loss_fn, opt, mesh, donate=False)
        new_params, _, metrics = step(params, opt_state, batch)

        # oracle: single-device full-batch step
        loss, grads = jax.value_and_grad(mlp_loss_fn)(params, batch)
        updates, _ = opt.update(grads, opt.init(params), params)
        import optax as _o
        oracle = _o.apply_updates(params, updates)

        np.testing.assert_allclose(float(metrics["loss"]), float(loss),
                                   rtol=1e-5)
        for kk in params:
            np.testing.assert_allclose(
                np.asarray(new_params[kk]), np.asarray(oracle[kk]),
                rtol=1e-5, atol=1e-6)
