"""SyncBatchNorm: cross-device statistics must equal global-batch
statistics (reference: horovod/torch/sync_batch_norm.py tests, which
assert sync-BN over N ranks == plain BN over the concatenated batch).
Round-1 verdict: sync_bn plumbing existed but NO test exercised BN
with a live axis — this is that test."""

import jax
import jax.numpy as jnp
from horovod_tpu.common.compat import shard_map
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd


def _bn_vars(num_features):
    return {
        "params": {"scale": jnp.full((num_features,), 1.5),
                   "bias": jnp.full((num_features,), 0.25)},
        "batch_stats": {"mean": jnp.zeros((num_features,)),
                        "var": jnp.ones((num_features,))},
    }


def test_sync_bn_matches_global_batch(eight_device_mesh):
    """8 shards with deliberately different per-shard distributions:
    synced BN output must match plain BN over the FULL batch, which
    per-shard (unsynced) BN provably does not."""
    mesh = eight_device_mesh
    n, per, feat = 8, 4, 6
    rng = np.random.RandomState(0)
    # shard i drawn from N(i, (i+1)^2): per-shard stats differ wildly
    x = np.stack([rng.normal(i, i + 1, size=(per, feat))
                  for i in range(n)]).astype(np.float32)

    sync_bn = hvd.SyncBatchNorm(use_running_average=False,
                                axis_name="proc")
    local_bn = hvd.SyncBatchNorm(use_running_average=False,
                                 axis_name=None)
    vars_ = _bn_vars(feat)

    def body(xs):
        y, _ = sync_bn.apply(vars_, xs[0], mutable=["batch_stats"])
        return y[None]

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("proc"), out_specs=P("proc")))
    g = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("proc")))
    out = np.asarray(f(g))                      # (n, per, feat)

    full = x.reshape(n * per, feat)
    ref, _ = local_bn.apply(_bn_vars(feat), jnp.asarray(full),
                            mutable=["batch_stats"])
    ref = np.asarray(ref).reshape(n, per, feat)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    # sanity: per-shard BN does NOT match -> the axis_name did the work
    unsynced, _ = local_bn.apply(
        _bn_vars(feat), jnp.asarray(x[0]), mutable=["batch_stats"])
    assert not np.allclose(np.asarray(unsynced), ref[0], atol=1e-3)


def test_sync_bn_running_stats_are_global(eight_device_mesh):
    """The running batch_stats written under axis_name must be the
    cross-device (global) moments, identical on every shard."""
    mesh = eight_device_mesh
    n, per, feat = 8, 8, 3
    rng = np.random.RandomState(1)
    x = rng.normal(2.0, 3.0, size=(n, per, feat)).astype(np.float32)

    bn = hvd.SyncBatchNorm(use_running_average=False, momentum=0.0,
                           axis_name="proc")
    vars_ = _bn_vars(feat)

    def body(xs):
        y, upd = bn.apply(vars_, xs[0], mutable=["batch_stats"])
        return y[None], (upd["batch_stats"]["mean"][None],
                         upd["batch_stats"]["var"][None])

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("proc"),
        out_specs=(P("proc"), (P("proc"), P("proc")))))
    g = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("proc")))
    _, (means, variances) = f(g)
    means = np.asarray(means)
    full = x.reshape(n * per, feat)
    # momentum=0 -> running stats equal this batch's global stats
    for i in range(n):
        np.testing.assert_allclose(means[i], full.mean(0), rtol=1e-4,
                                   atol=1e-5)
    v0 = np.asarray(variances)[0]
    np.testing.assert_allclose(v0, full.var(0), rtol=1e-3, atol=1e-4)


def test_resnet_sync_bn_axes_live(eight_device_mesh):
    """The resnet sync_bn_axes plumbing drives the same mechanism: a
    tiny ResNet with sync_bn_axes under shard_map runs and produces
    finite, shard-identical logits for identical inputs."""
    from horovod_tpu.models.resnet import ResNet
    mesh = eight_device_mesh
    model = ResNet(stage_sizes=[1], num_classes=4, num_filters=8,
                   dtype=jnp.float32, sync_bn_axes=("proc",))
    x_local = jnp.ones((2, 32, 32, 3))
    vars_ = model.init(jax.random.PRNGKey(0), x_local, train=True)

    def body(xs):
        logits, _ = model.apply(vars_, xs[0], train=True,
                                mutable=["batch_stats"])
        return logits[None]

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("proc"), out_specs=P("proc")))
    g = jax.device_put(
        jnp.broadcast_to(x_local, (8,) + x_local.shape),
        NamedSharding(mesh, P("proc")))
    out = np.asarray(f(g))
    assert np.all(np.isfinite(out))
    for i in range(1, 8):
        np.testing.assert_allclose(out[i], out[0], rtol=1e-5)
