"""Smoke-run every BASELINE example config (reference: the CI matrix
runs examples/ as tests; SURVEY.md §6 configs 1-5)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, args=(), np_=0, timeout=300, env_extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if env_extra:
        env.update(env_extra)
    if np_:
        cmd = [sys.executable, "-m", "horovod_tpu.runner", "-np",
               str(np_), sys.executable,
               os.path.join("examples", script), *args]
    else:
        cmd = [sys.executable, os.path.join("examples", script), *args]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


@pytest.mark.integration
class TestExamples:
    def test_mnist_single(self):
        r = run_example("mnist_mlp.py", ["--epochs", "2"])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "final train accuracy" in r.stdout

    def test_mnist_two_proc(self):
        r = run_example("mnist_mlp.py", ["--epochs", "1"], np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "epoch 0" in r.stdout

    def test_flax_train_state_two_proc(self):
        """The flax-idiom sugar path (DistributedTrainState.create)
        trains to accuracy at 2 ranks with rank-different init erased
        by the built-in broadcast."""
        r = run_example("flax_train_state.py", ["--epochs", "2"],
                        np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        acc = float(r.stdout.split("final train accuracy:")[1]
                    .strip().split()[0])
        assert acc > 0.9, r.stdout

    def test_torch_mnist_two_proc(self):
        """The reference's canonical torch script, one changed import
        (the torch frontend binding), trains to accuracy at 2 ranks."""
        r = run_example("torch_mnist.py", ["--epochs", "2"], np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        acc = float(r.stdout.split("final train accuracy:")[1]
                    .strip().split()[0])
        assert acc > 0.9, r.stdout

    def test_pipelined_two_proc(self):
        """The pipelined apply-then-grad recipe trains to accuracy
        through the negotiated grouped allreduce at 2 ranks."""
        r = run_example("pipelined_mlp.py", ["--epochs", "3"], np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "final train accuracy" in r.stdout
        acc = float(r.stdout.split("final train accuracy:")[1]
                    .strip().split()[0])
        assert acc > 0.9, r.stdout

    def test_resnet_synthetic(self):
        r = run_example("resnet50_synthetic.py",
                        ["--batch-size", "2", "--num-iters", "2",
                         "--num-warmup", "1", "--image-size", "32",
                         "--fp32"])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "Img/sec" in r.stdout

    def test_bert_fp16_fusion(self):
        r = run_example("bert_large_pretraining.py",
                        ["--steps", "2", "--batch-size", "2",
                         "--seq-len", "32"], np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "gradient tensors fused via fp16" in r.stdout

    def test_llama_adasum(self):
        r = run_example("llama2_7b_dp.py",
                        ["--steps", "2", "--batch-size", "2",
                         "--seq-len", "32"], np_=2)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "Adasum+fp16" in r.stdout

    def test_elastic_resnet(self, tmp_path):
        disc = tmp_path / "d.sh"
        disc.write_text("#!/bin/sh\necho localhost:2\n")
        disc.chmod(0o755)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner",
             "--host-discovery-script", str(disc),
             "--min-num-proc", "1",
             sys.executable, os.path.join("examples",
                                          "elastic_resnet50.py"),
             "--epochs", "1", "--batches-per-epoch", "2",
             "--image-size", "32", "--batch-size", "2",
             "--snapshot", str(tmp_path / "snap.bin")],
            cwd=REPO, env=env, capture_output=True, text=True,
            # ~230s alone (two CPU ResNet compiles); leave headroom
            # for a loaded machine running the full suite.
            timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "elastic training complete" in r.stdout


@pytest.mark.integration
class TestParallelismExamples:
    """SP/EP showcase examples on the 8-device virtual CPU mesh."""

    def test_ring_attention_long_context(self):
        r = run_example(
            "ring_attention_long_context.py",
            ["--seq-len", "256", "--heads", "2", "--head-dim", "16",
             "--verify"],
            env_extra={"XLA_FLAGS":
                       "--xla_force_host_platform_device_count=8"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "verified against full attention" in r.stdout

    def test_moe_expert_parallel(self):
        r = run_example(
            "moe_expert_parallel.py",
            ["--experts", "8", "--tokens", "64", "--d-model", "32",
             "--d-ff", "64"],
            env_extra={"XLA_FLAGS":
                       "--xla_force_host_platform_device_count=8"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "expert-parallel MoE OK" in r.stdout


@pytest.mark.integration
def test_serving_inference_chaos():
    """The serving example end to end with the injected mid-batch
    worker death: zero dropped requests is asserted inside the
    example and re-checked here."""
    r = run_example("serving_inference.py",
                    ["--chaos", "--requests", "60", "--qps", "400"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK (zero dropped requests)" in r.stdout
    assert "dropped=0" in r.stdout
