"""Shared bucketing layer (ops/bucketing.py) + the jit-path bucketed
overlap it feeds (parallel/train.py): partition determinism (SPMD
safety — byte-identical assignment for the same tree + threshold,
in-process and across a fresh interpreter), the reverse-order
property, threshold edge cases (oversized leaf, empty tree, zero
threshold, mixed dtypes via key_fn), and the train-step equivalences
the overlap path must preserve — bucketed == monolithic numerics,
guard flag-ride equivalence, the overlap-off HLO identity (byte-equal
to the pre-overlap builder) and overlap-on actually changing the
program, and the probe's span accounting."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.bucketing import (Bucket, assignment_digest,
                                       leaf_nbytes, partition_buckets,
                                       partition_tree, split_by_dtype)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _leaves():
    return [jnp.zeros(s, d) for s, d in
            [((8,), jnp.float32),      # 32 B
             ((4, 4), jnp.float32),    # 64 B
             ((2,), jnp.float32),      # 8 B
             ((16,), jnp.float32),     # 64 B
             ((3,), jnp.float32)]]     # 12 B


class TestPartition:
    def test_reverse_order_property(self):
        """Buckets walk the leaves last-first: bucket 0 starts at the
        LAST leaf, indices within a bucket strictly decrease, and the
        concatenation of all buckets is exactly reversed(range(n))."""
        buckets = partition_buckets(_leaves(), 80)
        flat = [i for b in buckets for i in b.indices]
        assert flat == list(range(len(_leaves()) - 1, -1, -1))
        for b in buckets:
            assert list(b.indices) == sorted(b.indices, reverse=True)

    def test_threshold_respected_and_bytes_accounted(self):
        buckets = partition_buckets(_leaves(), 80)
        for b in buckets:
            assert b.nbytes <= 80 or len(b.indices) == 1
            assert b.nbytes == sum(leaf_nbytes(_leaves()[i])
                                   for i in b.indices)

    def test_oversized_leaf_travels_alone(self):
        leaves = [jnp.zeros(4, jnp.float32),     # 16 B
                  jnp.zeros(100, jnp.float32),   # 400 B >> threshold
                  jnp.zeros(4, jnp.float32)]
        buckets = partition_buckets(leaves, 64)
        by_size = {b.indices: b.nbytes for b in buckets}
        assert (1,) in by_size and by_size[(1,)] == 400

    def test_empty_tree(self):
        assert partition_buckets([], 1024) == []
        assert partition_tree({}, 1024) == []

    def test_zero_threshold_disables_fusion(self):
        buckets = partition_buckets(_leaves(), 0)
        assert all(len(b.indices) == 1 for b in buckets)
        assert len(buckets) == len(_leaves())

    def test_scalar_leaf_counts_itemsize(self):
        assert leaf_nbytes(jnp.zeros((), jnp.float32)) == 4
        b = partition_buckets([jnp.zeros((), jnp.float64)], 1024)
        assert b == [Bucket(indices=(0,), nbytes=8)]

    def test_key_fn_families_never_share_a_bucket(self):
        leaves = [jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.int32),
                  jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.int32)]
        buckets = partition_buckets(
            leaves, 1 << 20, key_fn=lambda i, leaf: str(leaf.dtype))
        for b in buckets:
            assert len({str(leaves[i].dtype) for i in b.indices}) == 1
        # emission order still last-produced-first ACROSS families
        assert buckets[0].indices[0] == 3

    def test_split_by_dtype_preserves_order(self):
        xs = [jnp.zeros(1, jnp.float32), jnp.zeros(1, jnp.bfloat16),
              jnp.zeros(2, jnp.float32)]
        groups = split_by_dtype(xs)
        assert sorted(i for g in groups for i in g) == [0, 1, 2]
        assert [0, 2] in groups and [1] in groups

    def test_determinism_in_process(self):
        """Same shapes/dtypes/threshold => byte-identical digest, for
        independently constructed trees."""
        a = assignment_digest(partition_buckets(_leaves(), 80))
        b = assignment_digest(partition_buckets(_leaves(), 80))
        assert a == b
        # golden pin: the assignment itself is part of the SPMD
        # contract — a silent partitioner change would compile
        # different programs on different processes mid-rollout.
        assert a == "4,3:76;2,1:72;0:32"

    def test_determinism_across_processes(self):
        """A fresh interpreter derives the identical assignment — the
        SPMD-safety contract for cross-process compilation."""
        code = (
            "import jax.numpy as jnp\n"
            "from horovod_tpu.ops.bucketing import (partition_buckets,"
            " assignment_digest)\n"
            "leaves = [jnp.zeros(s, d) for s, d in"
            " [((8,), jnp.float32), ((4, 4), jnp.float32),"
            " ((2,), jnp.float32), ((16,), jnp.float32),"
            " ((3,), jnp.float32)]]\n"
            "print(assignment_digest(partition_buckets(leaves, 80)))\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == assignment_digest(
            partition_buckets(_leaves(), 80))


# ---------------------------------------------------------------------------
# bucketed overlap in build_train_step
# ---------------------------------------------------------------------------

def _mesh():
    return Mesh(np.array(jax.devices()[:8]), axis_names=("proc",))


def _loss(params, batch):
    h = jnp.tanh(batch[:, None] * params["w1"][None, :])
    return jnp.mean((h @ params["w2"]) ** 2) + jnp.mean(params["b"] ** 2)


def _params():
    return {"w1": jnp.arange(4.0), "w2": jnp.ones((4, 2)),
            "b": jnp.zeros(3)}


class TestBucketedTrainStep:
    def test_bucketed_matches_monolithic(self):
        from horovod_tpu.parallel.train import (build_train_step,
                                                last_overlap_info)
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = jnp.arange(8.0)
        s_on = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=16)
        p_on, _, m_on = s_on(params, st, batch)
        info = last_overlap_info()
        assert info["enabled"] and info["buckets"] >= 2
        assert sum(info["bucket_bytes"]) == sum(
            leaf_nbytes(v) for v in jax.tree_util.tree_leaves(params))
        s_off = build_train_step(_loss, opt, mesh, donate=False,
                                 overlap=False)
        p_off, _, m_off = s_off(params, st, batch)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_on[k]),
                                       np.asarray(p_off[k]), rtol=1e-6)
        np.testing.assert_allclose(float(m_on["loss"]),
                                   float(m_off["loss"]), rtol=1e-6)

    def test_world1_wire_gate_no_buckets(self, monkeypatch):
        """r08 wire gate: on a single-device mesh every leaf's reduce
        axes multiply out to 1 — the psum is the identity — so
        overlap-ON must build ZERO buckets and lower byte-identically
        to the monolithic program. This pins the fix for the
        single-chip copy tax the r08 attribution caught (+41 dead
        pack/psum/unpack instructions on the world-1 transformer
        step, benchmarks/PROFILE_transformer_r08.json): the bucket
        machinery may never again ship wire-less copies."""
        from horovod_tpu.parallel.train import (build_train_step,
                                                last_overlap_info)
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        mesh = Mesh(np.array(jax.devices()[:1]), axis_names=("proc",))
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = jnp.arange(8.0)
        s_on = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=16)
        on = s_on.lower(params, st, batch).as_text()
        info = last_overlap_info()
        assert info["enabled"] and info["buckets"] == 0, info
        s_off = build_train_step(_loss, opt, mesh, donate=False,
                                 overlap=False)
        off = s_off.lower(params, st, batch).as_text()
        assert on == off
        # and on a REAL multi-device mesh the gate must NOT fire
        s_multi = build_train_step(_loss, opt, _mesh(), donate=False,
                                   overlap=True, overlap_threshold=16)
        s_multi.lower(params, st, batch).as_text()
        assert last_overlap_info()["buckets"] >= 2

    def test_default_on_and_knob_off(self, monkeypatch):
        from horovod_tpu.parallel import train as T
        monkeypatch.delenv("HOROVOD_JIT_OVERLAP", raising=False)
        assert T.overlap_enabled() is True
        monkeypatch.setenv("HOROVOD_JIT_OVERLAP", "0")
        assert T.overlap_enabled() is False

    def test_overlap_off_hlo_identical_to_monolithic(self,
                                                     monkeypatch):
        """The off-switch restores TODAY'S program byte-for-byte: an
        explicitly-off build and a knob-off default build lower to
        identical HLO text (extends — does not weaken — the numerics
        HLO-identity test, which pins guard-off equality separately).
        Overlap ON must also genuinely change the program, or the
        knob is theater."""
        from horovod_tpu.parallel.train import build_train_step
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = jnp.arange(8.0)
        s_off = build_train_step(_loss, opt, mesh, donate=False,
                                 overlap=False)
        monkeypatch.setenv("HOROVOD_JIT_OVERLAP", "0")
        s_knob = build_train_step(_loss, opt, mesh, donate=False)
        monkeypatch.delenv("HOROVOD_JIT_OVERLAP", raising=False)
        s_on = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=16)
        off = s_off.lower(params, st, batch).as_text()
        knob = s_knob.lower(params, st, batch).as_text()
        on = s_on.lower(params, st, batch).as_text()
        assert off == knob
        assert on != off

    def test_guard_flag_rides_buckets_equivalently(self, monkeypatch):
        """Numerics flag-ride equivalence, bucketed vs monolithic: a
        NaN batch skips the step (update exactly zero) on both paths,
        and a clean step produces identical updates."""
        from horovod_tpu import numerics
        from horovod_tpu.parallel.train import build_train_step
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        mesh = _mesh()
        params = _params()
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        st = g.init(params)
        bad = jnp.arange(8.0).at[3].set(jnp.nan)
        clean = jnp.arange(8.0)
        results = {}
        for ov in (True, False):
            s = build_train_step(_loss, g, mesh, donate=False,
                                 overlap=ov, overlap_threshold=16)
            p_bad, o_bad, _ = s(params, st, bad)
            for k in params:
                np.testing.assert_array_equal(np.asarray(p_bad[k]),
                                              np.asarray(params[k]))
            assert numerics.consecutive_skips(o_bad) == 1
            p_ok, o_ok, _ = s(params, st, clean)
            assert numerics.consecutive_skips(o_ok) == 0
            results[ov] = p_ok
        for k in params:
            np.testing.assert_allclose(np.asarray(results[True][k]),
                                       np.asarray(results[False][k]),
                                       rtol=1e-6)

    def test_mixed_dtype_bucket_and_bf16_flag_routing(self,
                                                      monkeypatch):
        """bf16+f32 leaves share buckets (per-dtype wire arrays); the
        guard veto still lands even when a NaN hits only the bf16
        group (whose wire cannot carry an exact vote count)."""
        from horovod_tpu import numerics
        from horovod_tpu.parallel.train import build_train_step
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        mesh = _mesh()

        def loss2(params, batch):
            h = jnp.tanh(batch[:, None].astype(jnp.bfloat16)
                         * params["wb"][None, :])
            return jnp.mean((h.astype(jnp.float32) @ params["wf"])
                            ** 2)

        params = {"wb": jnp.ones(4, jnp.bfloat16),
                  "wf": jnp.ones((4, 2), jnp.float32)}
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        st = g.init(params)
        s = build_train_step(loss2, g, mesh, donate=False,
                             overlap=True, overlap_threshold=1 << 20)
        p, o, _ = s(params, st, jnp.arange(8.0))
        assert numerics.consecutive_skips(o) == 0
        assert float(jnp.abs(p["wf"] - params["wf"]).max()) > 0
        bad = dict(params, wb=params["wb"].at[0].set(jnp.nan))
        p2, o2, _ = s(bad, st, jnp.arange(8.0))
        assert numerics.consecutive_skips(o2) == 1
        np.testing.assert_array_equal(
            np.asarray(p2["wf"]), np.asarray(params["wf"]))

    def test_custom_grad_reducer_gets_summed_grads(self):
        """grad_reducer contract unchanged under overlap: it receives
        SUMMED gradients and owns scaling."""
        from horovod_tpu.parallel.train import build_train_step
        mesh = _mesh()
        opt = optax.sgd(1.0)
        params = {"w": jnp.zeros(3)}

        def loss(params, batch):
            return jnp.mean(batch) + jnp.sum(params["w"])

        seen = {}

        def reducer(grads):
            seen["called"] = True
            return jax.tree_util.tree_map(lambda g: g / 8.0, grads)

        st = opt.init(params)
        s = build_train_step(loss, opt, mesh, donate=False,
                             overlap=True, overlap_threshold=4,
                             grad_reducer=reducer)
        p, _, _ = s(params, st, jnp.arange(8.0))
        assert seen.get("called")
        # d(sum w)/dw = 1 per device, psum'd to 8, reducer /8 => step
        # of exactly -1.0 under sgd(1.0)
        np.testing.assert_allclose(np.asarray(p["w"]), -1.0,
                                   rtol=1e-6)

    def test_probe_records_interleaved_bucket_spans(self, tmp_path):
        """The overlap probe sees every bucket's ready->reduced pair
        in real execution order, reverse-bucket emission first, and
        its exposed-comm accounting + timeline spans are well-formed
        (the single-host face of the 2-proc merged-timeline
        artifact)."""
        from horovod_tpu import tracing
        from horovod_tpu.parallel.train import build_train_step
        from horovod_tpu.timeline import Timeline
        import time as _time
        probe = tracing.OverlapProbe()
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = jnp.arange(8.0)
        s = build_train_step(_loss, opt, mesh, donate=False,
                             overlap=True, overlap_threshold=16,
                             overlap_probe=probe)
        s(params, st, batch)          # compile cycle: NOT recorded
        assert probe.spans() == []
        probe.armed = True
        t0 = _time.monotonic_ns()
        out = s(params, st, batch)
        jax.block_until_ready(out)
        probe.step_span(t0, _time.monotonic_ns())
        probe.armed = False
        spans = probe.spans()
        n_buckets = 3
        assert len(spans) >= n_buckets
        assert {b for b, *_ in spans} == set(range(n_buckets))
        for _, t_ready, t_reduced, nb in spans:
            assert t_reduced >= t_ready and nb > 0
        acct = probe.hidden_fraction()
        assert acct["spans"] >= n_buckets
        assert 0.0 <= acct["exposed_comm_fraction"] <= 1.0
        tl = Timeline(str(tmp_path / "tl.json"))
        assert probe.to_timeline(tl) == len(spans)
        tl.close()
        doc = json.load(open(tmp_path / "tl.json"))
        reduces = [e for e in doc if e.get("name") == "REDUCE"]
        assert len(reduces) == 2 * len(spans)
        assert any(e.get("name") == "STEP" for e in doc)


# ---------------------------------------------------------------------------
# 2-rank integration: merged timeline with per-bucket reduce spans
# ---------------------------------------------------------------------------

@pytest.mark.integration
def test_two_rank_merged_timeline_shows_bucket_overlap(tmp_path):
    """Acceptance path: a 2-process run of the bucketed jit step with
    HOROVOD_TIMELINE + an armed OverlapProbe produces per-rank traces
    that merge into ONE clock-aligned trace whose overlap.bucketN
    REDUCE spans sit INSIDE the step's STEP envelope on both ranks —
    per-bucket reduction overlapping backprop compute, compile cycles
    excluded (the probe records only armed steps)."""
    tl_path = str(tmp_path / "overlap_tl.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_TIMELINE"] = tl_path
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, os.path.join("tests", "mp_worker_overlap.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip("this jaxlib's CPU backend cannot run cross-"
                    "process collectives (affects every multiprocess "
                    "integration test)")
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("OVERLAP WORKER OK") == 2

    from horovod_tpu import tracing
    merged_path, _report = tracing.merge(tl_path)
    doc = json.load(open(merged_path))
    evs = doc["traceEvents"]
    assert {0, 1} <= {e.get("pid") for e in evs}

    # per-rank: REDUCE spans exist and fall inside a STEP envelope
    for pid in (0, 1):
        mine = [e for e in evs if e.get("pid") == pid]
        tids = {}
        for e in mine:
            if e.get("name") == "thread_name":
                tids[e["tid"]] = e["args"]["name"]
        bucket_tids = {t for t, nm in tids.items()
                       if nm.startswith("overlap.bucket")}
        assert len(bucket_tids) >= 2, tids
        steps = [(b["ts"], e["ts"]) for b, e in zip(
            [x for x in mine if x.get("name") == "STEP"
             and x["ph"] == "B"],
            [x for x in mine if x.get("name") == "STEP"
             and x["ph"] == "E"])]
        assert steps
        reduces = [x for x in mine if x.get("name") == "REDUCE"
                   and x["ph"] == "B"]
        inside = [x for x in reduces
                  if any(b <= x["ts"] <= e for b, e in steps)]
        assert inside, (steps[:2], [x["ts"] for x in reduces][:4])
