"""hvdlint: the analyzer's own tests + the tier-1 repo gate.

Layout:
  * TestRepoGate — `horovod_tpu/` must be lint-clean (zero
    unsuppressed findings) and the run must stay fast (< 10 s), so
    the gate never becomes tier-1's slow step.
  * TestFixtureCorpus — every seeded positive in
    tests/lint_fixtures/ (marked `# EXPECT: HVD00x`) is reported at
    exactly that file:line, and nothing else is: positives, negatives
    and anchor accuracy in one assertion.
  * determinism / baseline round-trip / suppression parsing / CLI
    exit-code contract / config.env_value unit tests.
"""

import ast
import json
import os
import re
import time

import pytest

from horovod_tpu.analysis import run_analysis
from horovod_tpu.analysis import baseline as baseline_mod
from horovod_tpu.analysis import dataflow
from horovod_tpu.analysis import graph as graph_mod
from horovod_tpu.analysis import model as model_mod
from horovod_tpu.analysis.cli import main as cli_main
from horovod_tpu.analysis.model import (Project, Suppressions,
                                        collect_files)
from horovod_tpu.analysis.report import render_json, render_text
from horovod_tpu.common import config as hconfig

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "horovod_tpu")
FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(HVD\d+)")


def _expected_findings():
    """{(relpath, line, rule), ...} from the fixture EXPECT markers."""
    expected = set()
    for name in sorted(os.listdir(FIXTURES)):
        if not name.endswith(".py"):
            continue
        rel = f"tests/lint_fixtures/{name}"
        path = os.path.join(FIXTURES, name)
        with open(path, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, start=1):
                m = _EXPECT_RE.search(line)
                if m:
                    expected.add((rel, lineno, m.group(1)))
    return expected


class TestRepoGate:
    def test_repo_is_lint_clean(self):
        """The tier-1 gate: no unsuppressed findings in the package."""
        t0 = time.perf_counter()
        result = run_analysis([PKG], cwd=REPO_ROOT)
        elapsed = time.perf_counter() - t0
        assert result.parse_errors == []
        assert result.findings == [], (
            "new hvdlint findings (fix them or add a justified "
            "suppression):\n"
            + render_text(result.findings))
        # The gate must never become the slow step of tier-1.
        assert elapsed < 10.0, f"hvdlint took {elapsed:.1f}s (>10s)"

    def test_repo_suppressions_are_counted(self):
        """The audited benign findings are suppressed, not invisible —
        if this number drifts, someone added or removed a suppression
        and the PR should say why."""
        result = run_analysis([PKG], cwd=REPO_ROOT)
        assert result.suppressed >= 5


class TestFixtureCorpus:
    def test_seeded_positives_and_negatives(self):
        """Exactly the EXPECT-marked (file, line, rule) triples are
        reported — anchors included — and nothing else."""
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        got = {(f.path, f.line, f.rule) for f in result.findings}
        expected = _expected_findings()
        missing = expected - got
        extra = got - expected
        assert not missing, f"seeded violations not caught: {missing}"
        assert not extra, f"false positives: {extra}"

    def test_each_rule_has_positives(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        rules = {f.rule for f in result.findings}
        assert rules == {"HVD001", "HVD002", "HVD003", "HVD004",
                         "HVD005", "HVD006", "HVD008", "HVD009"}

    def test_fixture_suppressions_filtered(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert result.suppressed == 8


class TestDeterminism:
    def test_json_report_byte_stable(self):
        r1 = run_analysis([FIXTURES], cwd=REPO_ROOT)
        r2 = run_analysis([FIXTURES], cwd=REPO_ROOT)
        j1 = render_json(r1.findings, suppressed=r1.suppressed)
        j2 = render_json(r2.findings, suppressed=r2.suppressed)
        assert j1 == j2
        # and it parses back with stable ordering
        doc = json.loads(j1)
        keys = [(f["path"], f["line"], f["col"], f["rule"])
                for f in doc["findings"]]
        assert keys == sorted(keys)


class TestBaseline:
    def test_round_trip_filters_everything(self, tmp_path):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert result.findings
        text = baseline_mod.render(result.findings)
        # render -> parse -> filter: a committed baseline silences
        # exactly the findings it records
        baseline = baseline_mod.parse(text)
        again = run_analysis([FIXTURES], baseline=baseline,
                             cwd=REPO_ROOT)
        assert again.findings == []
        assert again.baselined == len(result.findings)

    def test_new_finding_still_fails(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        partial = baseline_mod.parse(
            baseline_mod.render(result.findings[1:]))
        again = run_analysis([FIXTURES], baseline=partial,
                             cwd=REPO_ROOT)
        assert len(again.findings) == 1
        assert (again.findings[0].fingerprint
                == result.findings[0].fingerprint)

    def test_render_is_stable(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert (baseline_mod.render(result.findings)
                == baseline_mod.render(list(result.findings)))


class TestSuppressions:
    def test_same_line(self):
        sup = Suppressions.parse(
            "x = 1  # hvdlint: disable=HVD002 (reason)\n")
        assert sup.covers("HVD002", 1)
        assert not sup.covers("HVD001", 1)
        assert not sup.covers("HVD002", 2)

    def test_disable_next_skips_comment_lines(self):
        sup = Suppressions.parse(
            "# hvdlint: disable-next=HVD001 (a reason that wraps\n"
            "# over several comment lines)\n"
            "do_thing()\n")
        assert sup.covers("HVD001", 3)
        assert not sup.covers("HVD001", 1)

    def test_multiple_rules_and_file_wide(self):
        sup = Suppressions.parse(
            "x  # hvdlint: disable=HVD001,HVD003\n"
            "# hvdlint: disable-file=HVD004\n")
        assert sup.covers("HVD001", 1)
        assert sup.covers("HVD003", 1)
        assert sup.covers("HVD004", 999)
        assert not sup.covers("HVD002", 1)

    def test_marker_inside_string_is_ignored(self):
        sup = Suppressions.parse(
            's = "# hvdlint: disable=HVD001"\n')
        assert not sup.covers("HVD001", 1)


class TestCli:
    def test_exit_codes_and_write_baseline(self, tmp_path, capsys,
                                           monkeypatch):
        monkeypatch.chdir(REPO_ROOT)
        bl = tmp_path / "bl.json"
        # findings without a baseline -> 1
        assert cli_main([FIXTURES, "--no-baseline"]) == 1
        capsys.readouterr()
        # write-baseline -> 0, then the same run against it -> 0
        assert cli_main([FIXTURES, "--write-baseline",
                         "--baseline", str(bl)]) == 0
        capsys.readouterr()
        assert cli_main([FIXTURES, "--baseline", str(bl)]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out
        # unknown rule -> usage error 2
        assert cli_main([FIXTURES, "--select", "HVD999"]) == 2
        capsys.readouterr()
        # a gate that scans nothing must fail loudly, not exit 0
        assert cli_main(["no/such/dir"]) == 2

    def test_github_format(self, capsys, monkeypatch):
        monkeypatch.chdir(REPO_ROOT)
        rc = cli_main([FIXTURES, "--no-baseline", "-f", "github"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "::error file=tests/lint_fixtures/" in out
        assert ",line=" in out

    def test_list_rules(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("HVD001", "HVD002", "HVD003", "HVD004",
                    "HVD005", "HVD006", "HVD007", "HVD008",
                    "HVD009"):
            assert rid in out

    def test_jaxpr_mode_exit_contract(self, tmp_path, capsys,
                                      monkeypatch):
        """`--jaxpr` runs the semantic tier through the same CLI
        contract: clean repo -> exit 0, cache file written next to
        the cwd."""
        monkeypatch.chdir(tmp_path)
        assert cli_main(["--jaxpr"]) == 0
        out = capsys.readouterr()
        assert "0 finding(s)" in out.out
        assert "config(s) verified" in out.err
        assert (tmp_path / ".hvdlint-jaxpr-cache.json").exists()


def _fixture_project():
    return Project(collect_files([FIXTURES], cwd=REPO_ROOT))


class TestCallGraph:
    """analysis/graph.py: resolution and the thread-entry index, run
    over the fixture corpus (no synthetic trees: the corpus is the
    contract)."""

    def test_self_method_resolution_and_thread_roots(self):
        g = graph_mod.get_call_graph(_fixture_project())
        rel = "tests/lint_fixtures/hvd006_lockset.py"
        pace = f"{rel}::DisjointLocks._pace"
        assert pace in g.funcs
        assert pace in g.thread_roots
        assert g.thread_roots[pace].kind == "thread"
        # signal handlers are entry points too
        sig = f"{rel}::_on_usr1"
        assert sig in g.thread_roots
        assert g.thread_roots[sig].kind == "signal"

    def test_entries_fold_main_and_roots(self):
        g = graph_mod.get_call_graph(_fixture_project())
        rel = "tests/lint_fixtures/hvd006_lockset.py"
        # the pacer body is thread-only; the public method is main-only
        assert g.entries(f"{rel}::DisjointLocks._pace") == frozenset(
            {f"{rel}::DisjointLocks._pace"})
        assert graph_mod.MAIN_ENTRY in g.entries(
            f"{rel}::DisjointLocks.bump")
        # a helper called from both sides carries both entries
        both = g.entries(f"{rel}::LockHeldAtEveryCallSite._bump_locked")
        assert graph_mod.MAIN_ENTRY in both
        assert f"{rel}::LockHeldAtEveryCallSite._pace" in both

    def test_cross_module_import_resolution(self):
        # hvd005 fixture calls collective_ops.synchronize through a
        # `from horovod_tpu.ops import collective_ops` alias; the
        # project must include that module for the edge to resolve.
        proj = Project(collect_files(
            [FIXTURES, os.path.join(PKG, "ops", "collective_ops.py")],
            cwd=REPO_ROOT))
        g = graph_mod.get_call_graph(proj)
        caller = ("tests/lint_fixtures/hvd005_protocol.py"
                  "::drained_on_one_branch_only")
        callees = g.edges.get(caller, set())
        assert ("horovod_tpu/ops/collective_ops.py::synchronize"
                in callees)

    def test_propagate_to_callers_is_bounded(self):
        g = graph_mod.get_call_graph(_fixture_project())
        rel = "tests/lint_fixtures/hvd005_protocol.py"
        seeds = {f"{rel}::_helper_submits": "allreduce"}
        out = g.propagate_to_callers(seeds, depth=2)
        assert f"{rel}::interprocedural_partial_protocol" in out
        assert out[f"{rel}::_helper_submits"] == "allreduce"


class TestDataflow:
    """CFG construction invariants the HVD005 detectors lean on."""

    @staticmethod
    def _fn(src):
        tree = ast.parse(src)
        return tree.body[0]

    def test_finally_is_cloned_onto_return_route(self):
        fn = self._fn(
            "def f(h):\n"
            "    try:\n"
            "        return 1\n"
            "    finally:\n"
            "        drain(h)\n")
        cfg = dataflow.build_cfg(fn)
        drain_stmt = fn.body[0].finalbody[0]
        # the finally body exists once on the normal path and once
        # cloned onto the return route
        assert len(cfg.nodes_of(drain_stmt)) >= 2

    def test_exit_avoiding_blocks_on_mentions(self):
        fn = self._fn(
            "def f(x):\n"
            "    h = go(x)\n"
            "    sync(h)\n"
            "    return x\n")
        cfg = dataflow.build_cfg(fn)
        assign, sync, ret = fn.body
        starts = [s for i in cfg.nodes_of(assign)
                  for s in cfg.nodes[i].succs]
        avoid = set(cfg.nodes_of(sync))
        assert not cfg.exit_reachable_avoiding(starts, avoid)
        assert cfg.exit_reachable_avoiding(starts, set())

    def test_while_true_has_no_fall_through(self):
        fn = self._fn(
            "def f():\n"
            "    while True:\n"
            "        step()\n"
            "    after()\n")
        cfg = dataflow.build_cfg(fn)
        after = fn.body[1]
        # `after()` is unreachable: no edges lead into it
        targets = {s for n in cfg.nodes for s in n.succs}
        assert all(i not in targets
                   for i in cfg.nodes_of(after))

    def test_always_raises(self):
        h = ast.parse(
            "try:\n    x()\nexcept E:\n    log()\n    raise\n")
        handler = h.body[0].handlers[0]
        assert dataflow.always_raises(handler.body)
        h2 = ast.parse(
            "try:\n    x()\nexcept E:\n    log()\n")
        assert not dataflow.always_raises(h2.body[0].handlers[0].body)


class TestHistoricalRegressions:
    """The bugs this repo actually shipped (PR 1 race, PR 4
    Popen-under-lock, PR 6 handle leak, PR 18's schema drift and
    byte-identity flake; PR 8's two jaxpr-level defects)
    reconstructed in tests/lint_fixtures/hvd_regressions.py must
    each be caught by the tier that owns them."""

    def test_ast_tier_regressions_are_flagged(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        rel = "tests/lint_fixtures/hvd_regressions.py"
        got = {(f.rule, f.context) for f in result.findings
               if f.path == rel}
        assert ("HVD006",
                "Pr1BytesProcessedRace._dispatch_loop") in got
        assert ("HVD003", "Pr4PopenUnderLock.spawn") in got
        assert ("HVD005", "Pr6HandleLeak.step") in got
        # PR 18 schema drift: the doctor read a misspelled watermark
        # field and silently counted nothing.
        assert ("HVD008", "pr18_watermark_field_drift") in got
        # PR 18 byte-identity flake: unsorted glob in the trajectory
        # consolidation walk.
        assert ("HVD009", "pr18_trajectory_consolidate") in got

    @staticmethod
    def _fixture_module():
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "hvd_regressions_fixture",
            os.path.join(FIXTURES, "hvd_regressions.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_round8_wire_gate_bug_is_flagged(self):
        """PR 8 bug #1 (size-1-axis psum at world 1) as a traced
        program: invisible to every AST rule, caught by HVD007."""
        from horovod_tpu.analysis.jaxpr_verify import verify_traced
        mod = self._fixture_module()
        step, args, mesh_shape = mod.pr8_wire_gate_builder()
        msgs = verify_traced(step, args, mesh_shape)
        assert any("size-1" in m for m in msgs), msgs

    def test_round8_double_reduce_bug_is_flagged(self):
        """PR 8 bug #2 (legacy psum-transpose over-count) as a traced
        program: HVD007's reduced-axes dataflow names the axis."""
        from horovod_tpu.analysis.jaxpr_verify import verify_traced
        mod = self._fixture_module()
        step, args, mesh_shape = mod.pr8_legacy_double_reduce_builder()
        msgs = verify_traced(step, args, mesh_shape)
        assert any("double reduction" in m for m in msgs), msgs

    def test_round13_flag_on_lossy_carrier_is_flagged(self):
        """PR 13 (first compression draft) as a traced program: the
        finite-flag riding the fp16 wire carrier. HVD007's check (e)
        must flag both the planned ride and the absent exact f32
        vote."""
        from horovod_tpu.analysis.jaxpr_verify import verify_traced
        mod = self._fixture_module()
        (step, args, mesh_shape,
         plan) = mod.pr13_flag_rides_compressed_carrier_builder()
        msgs = verify_traced(step, args, mesh_shape,
                             numerics_guard=True, plan=plan)
        assert any("riding its lossy wire carrier" in m
                   for m in msgs), msgs
        assert any("no separate exact f32 vote" in m
                   for m in msgs), msgs


class TestChangedOnly:
    def test_focus_restricts_findings_to_neighbors(self):
        changed = {"tests/lint_fixtures/hvd006_lockset.py"}
        result = run_analysis([FIXTURES], cwd=REPO_ROOT,
                              focus_from=changed)
        assert result.findings  # the lockset positives survive
        assert {f.path for f in result.findings} <= {
            "tests/lint_fixtures/hvd006_lockset.py"}
        full = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert len(result.findings) < len(full.findings)

    def test_neighbors_include_callees(self):
        proj = _fixture_project()
        out = graph_mod.focus_neighbors(
            proj, {"tests/lint_fixtures/hvd005_protocol.py"})
        assert "tests/lint_fixtures/hvd005_protocol.py" in out
        # hvd006 fixture has no call edges to hvd005: not a neighbor
        assert "tests/lint_fixtures/hvd006_lockset.py" not in out

    def test_empty_changed_set_reports_nothing(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT,
                              focus_from=set())
        assert result.findings == []
        assert result.file_count > 0


class TestOverheadGuard:
    """The interprocedural pass must not make the gate the slow step:
    parsed modules and call graphs are cached on content hashes, so a
    re-run over an unchanged tree re-parses nothing."""

    def test_second_run_is_all_cache_hits(self):
        run_analysis([FIXTURES], cwd=REPO_ROOT)  # warm
        before = model_mod.cache_stats()
        g_before = graph_mod.cache_stats()
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        after = model_mod.cache_stats()
        g_after = graph_mod.cache_stats()
        assert after["misses"] == before["misses"], \
            "unchanged sources were re-parsed"
        assert after["hits"] >= before["hits"] + result.file_count
        assert g_after["misses"] == g_before["misses"], \
            "unchanged project re-indexed its call graph"

    def test_repo_gate_budget_with_interprocedural_pass(self):
        # cold-ish path is covered by TestRepoGate's <10 s assert;
        # the warm path must be far cheaper than the budget
        run_analysis([PKG], cwd=REPO_ROOT)  # warm
        t0 = time.perf_counter()
        result = run_analysis([PKG], cwd=REPO_ROOT)
        elapsed = time.perf_counter() - t0
        assert result.file_count > 0
        assert elapsed < 5.0, (
            f"warm interprocedural run took {elapsed:.1f}s")


class TestEnvValue:
    def test_declared_typed_read(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1024")
        assert hconfig.env_value("HOROVOD_FUSION_THRESHOLD") == 1024

    def test_default_on_unset_and_empty(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_ELASTIC_TIMEOUT", raising=False)
        assert hconfig.env_value("HOROVOD_ELASTIC_TIMEOUT") == 600.0
        monkeypatch.setenv("HOROVOD_ELASTIC_TIMEOUT", "")
        assert hconfig.env_value("HOROVOD_ELASTIC_TIMEOUT") == 600.0

    def test_undeclared_raises(self):
        with pytest.raises(KeyError):
            hconfig.env_value("HOROVOD_NOT_A_KNOB")

    def test_bad_value_raises(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "bogus")
        with pytest.raises(ValueError):
            hconfig.env_value("HOROVOD_FUSION_THRESHOLD")

    def test_explicit_env_dict(self):
        assert hconfig.env_value(
            "HOROVOD_ELASTIC_EPOCH", env={"HOROVOD_ELASTIC_EPOCH":
                                          "7"}) == 7


class TestJaxprTier:
    """HVD007 — the semantic tier's tier-1 gate: the full builder
    matrix must verify clean inside a wall-clock budget, the
    source-hash cache must make warm runs free, and the matrix must
    actually cover the advertised cells."""

    def test_repo_is_hvd007_clean_across_full_matrix(self):
        from horovod_tpu.analysis import jaxpr_verify
        t0 = time.perf_counter()
        result = jaxpr_verify.run_jaxpr_analysis(cwd=REPO_ROOT,
                                                 use_cache=False)
        elapsed = time.perf_counter() - t0
        assert result.findings == [], (
            "HVD007 findings on the repo's builders:\n"
            + render_text(result.findings))
        # the acceptance floor: the full (world x overlap x numerics)
        # grid plus the shape extras and the eager plan
        assert result.file_count >= 12, result.meta
        assert result.meta["configs_skipped"] == [], result.meta
        # time budget: tracing is zero-FLOP, this must never become
        # tier-1's slow step
        assert elapsed < 120.0, f"jaxpr tier took {elapsed:.1f}s"

    def test_matrix_covers_required_cells(self):
        from horovod_tpu.analysis.jaxpr_verify import default_matrix
        names = [c.name for c in default_matrix()]
        for world in (1, 2, 8):
            for ov in ("on", "off"):
                for nm in ("on", "off"):
                    assert (f"world={world},overlap={ov},"
                            f"numerics={nm}") in names
        assert sum("eager-plan" in n for n in names) >= 2
        assert any("tensor1" in n for n in names)   # trivial axis
        assert any("bfloat16" in n for n in names)  # separate vote

    def test_cache_hit_and_source_key_invalidation(self, tmp_path,
                                                   monkeypatch):
        from horovod_tpu.analysis import jaxpr_verify
        cache = tmp_path / "jaxpr-cache.json"
        r1 = jaxpr_verify.run_jaxpr_analysis(cwd=REPO_ROOT,
                                             cache_path=str(cache))
        assert cache.exists()
        before = jaxpr_verify.cache_stats()
        r2 = jaxpr_verify.run_jaxpr_analysis(cwd=REPO_ROOT,
                                             cache_path=str(cache))
        after = jaxpr_verify.cache_stats()
        assert after["hits"] == before["hits"] + 1, (before, after)
        assert r2.file_count == r1.file_count
        assert r2.meta["cache"] == "hit"
        # key must move when a dependency source changes
        dep = tmp_path / "fake_dep.py"
        dep.write_text("a = 1\n")
        real = jaxpr_verify._dependency_files()
        monkeypatch.setattr(jaxpr_verify, "_dependency_files",
                            lambda: real + [str(dep)])
        k1 = jaxpr_verify.source_cache_key()
        dep.write_text("a = 2\n")
        k2 = jaxpr_verify.source_cache_key()
        assert k1 != k2

    def test_plan_digest_ties_builder_to_introspection(self):
        """The digest the traced builder records at trace time is the
        digest plan_overlap computes offline — one authority for the
        SPMD cross-process contract."""
        import jax
        import numpy as np
        import optax
        from jax.sharding import Mesh

        from horovod_tpu.parallel.train import (build_train_step,
                                                last_overlap_info,
                                                plan_overlap)

        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        params = {"a": np.zeros((4, 4), np.float32),
                  "b": np.zeros((3,), np.float32)}
        opt = optax.sgd(0.1)
        st = opt.init(params)

        def loss(p, batch):
            import jax.numpy as jnp
            return jnp.mean((batch[:, None] * p["a"]).sum(-1)
                            + p["b"].sum())

        s = build_train_step(loss, opt, mesh, donate=False,
                             overlap=True, overlap_threshold=32)
        s.lower(params, st, np.zeros((8, 4), np.float32))
        info = last_overlap_info()
        plan = plan_overlap(params, mesh, overlap_threshold=32,
                            guard=False)
        assert info["digest"] == plan.digest
        assert info["buckets"] == len(plan.bucket_leaf_indices)

    def test_wire_groups_account_flag_ride(self):
        """Numerics on: the plan's exact-count carrier group grows by
        exactly one element; bf16-only buckets never ride."""
        import numpy as np
        from jax.sharding import Mesh
        import jax

        from horovod_tpu.parallel.train import plan_overlap

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        f32 = {"w": np.zeros((4,), np.float32)}
        p = plan_overlap(f32, mesh, overlap_threshold=1 << 20,
                         guard=True)
        (wg,) = p.wire[0]
        assert wg.rides_flag and wg.n == 5  # 4 payload + flag
        bf16 = {"w": jax.ShapeDtypeStruct((4,), jax.numpy.bfloat16)}
        p2 = plan_overlap(bf16, mesh, overlap_threshold=1 << 20,
                          guard=True)
        (wg2,) = p2.wire[0]
        assert not wg2.rides_flag and wg2.n == 4


class TestDocsDrift:
    """HVD002 invariant 4: the user_guide knob tables vs the
    registry."""

    @staticmethod
    def _project(tmp_path, doc_rows, registry_dir="pkg/common"):
        reg_dir = tmp_path / registry_dir
        reg_dir.mkdir(parents=True)
        (reg_dir / "config.py").write_text(
            "KNOBS = [\n"
            "    Knob('HOROVOD_ALPHA', int, 64 * 1024, 'doc'),\n"
            "    Knob('HOROVOD_BETA', _parse_bool, True, 'doc'),\n"
            "    Knob('HOROVOD_GAMMA', str, '', 'doc'),\n"
            "]\n"
            # uses, so the unused-knob check stays quiet
            "_ATTR_MAP = {}\n")
        (tmp_path / "docs").mkdir(exist_ok=True)
        (tmp_path / "docs" / "user_guide.md").write_text(
            "| Knob | Default | What |\n|---|---|---|\n"
            + "\n".join(doc_rows) + "\n")
        root = str(tmp_path / registry_dir.split("/")[0])
        return run_analysis([root], cwd=str(tmp_path))

    def test_stale_row_and_default_drift_flagged(self, tmp_path):
        result = self._project(tmp_path, [
            "| `HOROVOD_ALPHA` | 9999 | wrong default |",
            "| `HOROVOD_BETA` | 1 | agrees (bool spellings) |",
            "| `HOROVOD_GAMMA` | (launcher-set) | empty default: "
            "not checkable |",
            "| `HOROVOD_GONE` | 3 | stale row |",
        ])
        doc = [f for f in result.findings
               if f.path == "docs/user_guide.md"]
        msgs = [f.message for f in doc]
        assert any("HOROVOD_GONE" in m and "stale" in m
                   for m in msgs), msgs
        assert any("HOROVOD_ALPHA" in m and "drift" in m
                   for m in msgs), msgs
        assert not any("HOROVOD_BETA" in m for m in msgs), msgs
        assert not any("HOROVOD_GAMMA" in m for m in msgs), msgs

    def test_arith_default_spellings_accepted(self, tmp_path):
        result = self._project(tmp_path, [
            "| `HOROVOD_ALPHA` | 65536 | folded 64 * 1024 |",
        ])
        assert not [f for f in result.findings
                    if f.path == "docs/user_guide.md"]

    def test_non_common_registry_skips_docs(self, tmp_path):
        """A registry outside a common/ dir (e.g. the lint fixtures)
        must never scan a docs tree it does not own."""
        result = self._project(tmp_path, [
            "| `HOROVOD_GONE` | 3 | would be stale |",
        ], registry_dir="pkg/lint_fixtures")
        assert not [f for f in result.findings
                    if f.path == "docs/user_guide.md"]
