"""hvdlint: the analyzer's own tests + the tier-1 repo gate.

Layout:
  * TestRepoGate — `horovod_tpu/` must be lint-clean (zero
    unsuppressed findings) and the run must stay fast (< 10 s), so
    the gate never becomes tier-1's slow step.
  * TestFixtureCorpus — every seeded positive in
    tests/lint_fixtures/ (marked `# EXPECT: HVD00x`) is reported at
    exactly that file:line, and nothing else is: positives, negatives
    and anchor accuracy in one assertion.
  * determinism / baseline round-trip / suppression parsing / CLI
    exit-code contract / config.env_value unit tests.
"""

import json
import os
import re
import time

import pytest

from horovod_tpu.analysis import run_analysis
from horovod_tpu.analysis import baseline as baseline_mod
from horovod_tpu.analysis.cli import main as cli_main
from horovod_tpu.analysis.model import Suppressions
from horovod_tpu.analysis.report import render_json, render_text
from horovod_tpu.common import config as hconfig

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "horovod_tpu")
FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(HVD\d+)")


def _expected_findings():
    """{(relpath, line, rule), ...} from the fixture EXPECT markers."""
    expected = set()
    for name in sorted(os.listdir(FIXTURES)):
        if not name.endswith(".py"):
            continue
        rel = f"tests/lint_fixtures/{name}"
        path = os.path.join(FIXTURES, name)
        with open(path, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, start=1):
                m = _EXPECT_RE.search(line)
                if m:
                    expected.add((rel, lineno, m.group(1)))
    return expected


class TestRepoGate:
    def test_repo_is_lint_clean(self):
        """The tier-1 gate: no unsuppressed findings in the package."""
        t0 = time.perf_counter()
        result = run_analysis([PKG], cwd=REPO_ROOT)
        elapsed = time.perf_counter() - t0
        assert result.parse_errors == []
        assert result.findings == [], (
            "new hvdlint findings (fix them or add a justified "
            "suppression):\n"
            + render_text(result.findings))
        # The gate must never become the slow step of tier-1.
        assert elapsed < 10.0, f"hvdlint took {elapsed:.1f}s (>10s)"

    def test_repo_suppressions_are_counted(self):
        """The audited benign findings are suppressed, not invisible —
        if this number drifts, someone added or removed a suppression
        and the PR should say why."""
        result = run_analysis([PKG], cwd=REPO_ROOT)
        assert result.suppressed >= 5


class TestFixtureCorpus:
    def test_seeded_positives_and_negatives(self):
        """Exactly the EXPECT-marked (file, line, rule) triples are
        reported — anchors included — and nothing else."""
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        got = {(f.path, f.line, f.rule) for f in result.findings}
        expected = _expected_findings()
        missing = expected - got
        extra = got - expected
        assert not missing, f"seeded violations not caught: {missing}"
        assert not extra, f"false positives: {extra}"

    def test_each_rule_has_positives(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        rules = {f.rule for f in result.findings}
        assert rules == {"HVD001", "HVD002", "HVD003", "HVD004"}

    def test_fixture_suppressions_filtered(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert result.suppressed == 4


class TestDeterminism:
    def test_json_report_byte_stable(self):
        r1 = run_analysis([FIXTURES], cwd=REPO_ROOT)
        r2 = run_analysis([FIXTURES], cwd=REPO_ROOT)
        j1 = render_json(r1.findings, suppressed=r1.suppressed)
        j2 = render_json(r2.findings, suppressed=r2.suppressed)
        assert j1 == j2
        # and it parses back with stable ordering
        doc = json.loads(j1)
        keys = [(f["path"], f["line"], f["col"], f["rule"])
                for f in doc["findings"]]
        assert keys == sorted(keys)


class TestBaseline:
    def test_round_trip_filters_everything(self, tmp_path):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert result.findings
        text = baseline_mod.render(result.findings)
        # render -> parse -> filter: a committed baseline silences
        # exactly the findings it records
        baseline = baseline_mod.parse(text)
        again = run_analysis([FIXTURES], baseline=baseline,
                             cwd=REPO_ROOT)
        assert again.findings == []
        assert again.baselined == len(result.findings)

    def test_new_finding_still_fails(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        partial = baseline_mod.parse(
            baseline_mod.render(result.findings[1:]))
        again = run_analysis([FIXTURES], baseline=partial,
                             cwd=REPO_ROOT)
        assert len(again.findings) == 1
        assert (again.findings[0].fingerprint
                == result.findings[0].fingerprint)

    def test_render_is_stable(self):
        result = run_analysis([FIXTURES], cwd=REPO_ROOT)
        assert (baseline_mod.render(result.findings)
                == baseline_mod.render(list(result.findings)))


class TestSuppressions:
    def test_same_line(self):
        sup = Suppressions.parse(
            "x = 1  # hvdlint: disable=HVD002 (reason)\n")
        assert sup.covers("HVD002", 1)
        assert not sup.covers("HVD001", 1)
        assert not sup.covers("HVD002", 2)

    def test_disable_next_skips_comment_lines(self):
        sup = Suppressions.parse(
            "# hvdlint: disable-next=HVD001 (a reason that wraps\n"
            "# over several comment lines)\n"
            "do_thing()\n")
        assert sup.covers("HVD001", 3)
        assert not sup.covers("HVD001", 1)

    def test_multiple_rules_and_file_wide(self):
        sup = Suppressions.parse(
            "x  # hvdlint: disable=HVD001,HVD003\n"
            "# hvdlint: disable-file=HVD004\n")
        assert sup.covers("HVD001", 1)
        assert sup.covers("HVD003", 1)
        assert sup.covers("HVD004", 999)
        assert not sup.covers("HVD002", 1)

    def test_marker_inside_string_is_ignored(self):
        sup = Suppressions.parse(
            's = "# hvdlint: disable=HVD001"\n')
        assert not sup.covers("HVD001", 1)


class TestCli:
    def test_exit_codes_and_write_baseline(self, tmp_path, capsys,
                                           monkeypatch):
        monkeypatch.chdir(REPO_ROOT)
        bl = tmp_path / "bl.json"
        # findings without a baseline -> 1
        assert cli_main([FIXTURES, "--no-baseline"]) == 1
        capsys.readouterr()
        # write-baseline -> 0, then the same run against it -> 0
        assert cli_main([FIXTURES, "--write-baseline",
                         "--baseline", str(bl)]) == 0
        capsys.readouterr()
        assert cli_main([FIXTURES, "--baseline", str(bl)]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out
        # unknown rule -> usage error 2
        assert cli_main([FIXTURES, "--select", "HVD999"]) == 2
        capsys.readouterr()
        # a gate that scans nothing must fail loudly, not exit 0
        assert cli_main(["no/such/dir"]) == 2

    def test_github_format(self, capsys, monkeypatch):
        monkeypatch.chdir(REPO_ROOT)
        rc = cli_main([FIXTURES, "--no-baseline", "-f", "github"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "::error file=tests/lint_fixtures/" in out
        assert ",line=" in out

    def test_list_rules(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("HVD001", "HVD002", "HVD003", "HVD004"):
            assert rid in out


class TestEnvValue:
    def test_declared_typed_read(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1024")
        assert hconfig.env_value("HOROVOD_FUSION_THRESHOLD") == 1024

    def test_default_on_unset_and_empty(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_ELASTIC_TIMEOUT", raising=False)
        assert hconfig.env_value("HOROVOD_ELASTIC_TIMEOUT") == 600.0
        monkeypatch.setenv("HOROVOD_ELASTIC_TIMEOUT", "")
        assert hconfig.env_value("HOROVOD_ELASTIC_TIMEOUT") == 600.0

    def test_undeclared_raises(self):
        with pytest.raises(KeyError):
            hconfig.env_value("HOROVOD_NOT_A_KNOB")

    def test_bad_value_raises(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "bogus")
        with pytest.raises(ValueError):
            hconfig.env_value("HOROVOD_FUSION_THRESHOLD")

    def test_explicit_env_dict(self):
        assert hconfig.env_value(
            "HOROVOD_ELASTIC_EPOCH", env={"HOROVOD_ELASTIC_EPOCH":
                                          "7"}) == 7
