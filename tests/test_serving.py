"""Elastic inference serving tests: bucket-ladder determinism (incl.
across fresh interpreters), the no-recompile pin under mixed request
shapes, dynamic batching under the latency budget, retry exactly-once
semantics under injected `serving.batch` faults (error / hang /
exhausted budget), queue-depth autoscaling, the ElasticDriver
membership hook, a real mid-batch remote-worker kill over the wire
(zero dropped requests), the committed serving bench artifact's pins,
and (behind the multiproc probe) a 2-rank chaos run through the
elastic runner."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import faults, journal
from horovod_tpu.common import config
from horovod_tpu.serving import (ServingError, ServingFrontend,
                                 build_ladder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_ARTIFACT = os.path.join(REPO, "benchmarks",
                              "BENCH_serving_r15.json")

D = 8  # feature width used by every frontend in this file


def _forward(x):
    import jax.numpy as jnp
    return jnp.tanh(x) * 2.0


def _expect(x):
    return np.tanh(np.asarray(x, dtype=np.float32)) * 2.0


@pytest.fixture(autouse=True)
def _clean_fault_and_journal_state():
    """Frontends (re)configure the module journal and tests arm the
    fault plan; restore both so state never leaks across tests."""
    yield
    faults.configure("", seed=0)
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None


def _base_env(tmp_path=None, **over):
    env = {
        "HOROVOD_SERVING_MAX_BATCH": "4",
        "HOROVOD_SERVING_LATENCY_BUDGET_MS": "5",
        "HOROVOD_SERVING_MIN_WORKERS": "1",
        "HOROVOD_SERVING_MAX_WORKERS": "4",
        "HOROVOD_SERVING_SCALE_INTERVAL_S": "0.05",
        "HOROVOD_SERVING_WORKER_TIMEOUT_S": "30",
    }
    if tmp_path is not None:
        jdir = os.path.join(str(tmp_path), "journal")
        os.makedirs(jdir, exist_ok=True)
        env["HOROVOD_JOURNAL_DIR"] = jdir
    env.update({k: str(v) for k, v in over.items()})
    return env


def _journal_events(tmp_path, role="serving"):
    path = os.path.join(str(tmp_path), "journal",
                        f"journal-{role}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# -- bucket ladder ---------------------------------------------------------


class TestBucketLadder:
    def test_pow2_rungs_and_rounding(self):
        lad = build_ladder(max_batch=8, max_len=0)
        assert lad.batch_buckets == (1, 2, 4, 8)
        assert lad.len_buckets == ()
        assert [lad.batch_bucket(n) for n in (1, 2, 3, 5, 8)] == \
            [1, 2, 4, 8, 8]

    def test_non_pow2_max_is_its_own_rung(self):
        lad = build_ladder(max_batch=6, max_len=0)
        assert lad.batch_buckets == (1, 2, 4, 6)
        assert lad.batch_bucket(5) == 6

    def test_oversize_raises_visibly(self):
        lad = build_ladder(max_batch=4, max_len=32)
        with pytest.raises(ServingError):
            lad.batch_bucket(5)
        with pytest.raises(ServingError):
            lad.len_bucket(33)

    def test_len_ladder_variants(self):
        assert build_ladder(4, 8).len_buckets == (8,)
        assert build_ladder(4, 16).len_buckets == (16,)
        assert build_ladder(4, 48).len_buckets == (16, 32, 48)
        assert build_ladder(4, 64).len_buckets == (16, 32, 64)

    def test_digest_is_canonical_string(self):
        assert build_ladder(8, 0).digest == \
            "serving-ladder-v1|b=1,2,4,8|l=-"
        assert build_ladder(4, 48).digest == \
            "serving-ladder-v1|b=1,2,4|l=16,32,48"

    def test_shapes_enumerates_full_cross_product(self):
        lad = build_ladder(4, 32)
        shapes = lad.shapes((D,))
        assert len(shapes) == 3 * 2
        assert (4, 32, D) in shapes and (1, 16, D) in shapes
        assert build_ladder(2, 0).shapes((D,)) == [(1, D), (2, D)]

    def test_knob_driven_build(self):
        lad = build_ladder(env={"HOROVOD_SERVING_MAX_BATCH": "16",
                                "HOROVOD_SERVING_MAX_LEN": "0"})
        assert lad.batch_buckets == (1, 2, 4, 8, 16)

    def test_digest_deterministic_across_fresh_interpreters(self):
        """The cross-process pin: frontends and workers must derive
        the identical digest in separate interpreters regardless of
        hash randomization (same contract as OverlapPlan's assignment
        digest)."""
        prog = ("import sys; sys.path.insert(0, sys.argv[1]); "
                "from horovod_tpu.serving import build_ladder; "
                "l = build_ladder(8, 48); "
                "print(l.digest); print(l.shapes((8,)))")
        outs = []
        for seed in ("0", "4242"):
            env = dict(os.environ)
            env["PYTHONHASHSEED"] = seed
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            r = subprocess.run(
                [sys.executable, "-c", prog, REPO], env=env,
                capture_output=True, text=True, timeout=120)
            assert r.returncode == 0, r.stderr
            outs.append(r.stdout)
        assert outs[0] == outs[1]
        assert outs[0].splitlines()[0] == build_ladder(8, 48).digest


def test_all_serving_knobs_declared():
    """Every HOROVOD_SERVING_* tunable is a declared knob (the HVD002
    registry/docs-drift gate hangs off this list)."""
    declared = {k.env: k for k in config.KNOBS}
    expected = {
        "HOROVOD_SERVING_MAX_BATCH": 8,
        "HOROVOD_SERVING_LATENCY_BUDGET_MS": 10.0,
        "HOROVOD_SERVING_MAX_LEN": 0,
        "HOROVOD_SERVING_MIN_WORKERS": 1,
        "HOROVOD_SERVING_MAX_WORKERS": 4,
        "HOROVOD_SERVING_SCALE_INTERVAL_S": 0.5,
        "HOROVOD_SERVING_SCALE_UP_QUEUE": 2.0,
        "HOROVOD_SERVING_SCALE_DOWN_IDLE_S": 5.0,
        "HOROVOD_SERVING_RETRY_LIMIT": 3,
        "HOROVOD_SERVING_WORKER_TIMEOUT_S": 30.0,
        "HOROVOD_SERVING_TRACE": True,
        "HOROVOD_SERVING_TRACE_BUFFER": 4096,
        "HOROVOD_SERVING_DEFAULT_SLO_MS": 0.0,
    }
    for name, default in expected.items():
        assert name in declared, name
        assert declared[name].default == default, name


# -- local frontend --------------------------------------------------------


class TestFrontendLocal:
    def test_round_trip_and_dynamic_batching(self, tmp_path):
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(1)
            rng = np.random.RandomState(0)
            xs = [rng.randn(D).astype(np.float32) for _ in range(10)]
            futs = [fe.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                np.testing.assert_allclose(
                    f.result(timeout=60), _expect(x),
                    rtol=1e-5, atol=1e-5)
            s = fe.stats()
        finally:
            fe.close()
        assert s["submitted"] == 10
        assert s["completed"] == 10
        assert s["dropped"] == 0 and s["failed"] == 0
        # MAX_BATCH=4 => at least ceil(10/4) dynamic batches
        assert s["batches"] >= 3
        evs = _journal_events(tmp_path)
        admitted = [e for e in evs if e["type"] == "batch_admitted"]
        assert sum(e["size"] for e in admitted) == 10
        for e in admitted:
            assert e["bucket"] >= e["size"]

    def test_latency_budget_cuts_partial_batch(self):
        # A batch that can never fill must still complete within the
        # latency budget (plus execution), not wait forever.
        env = _base_env(None, HOROVOD_SERVING_MAX_BATCH=64,
                        HOROVOD_SERVING_LATENCY_BUDGET_MS=30)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(1)
            futs = [fe.submit(np.ones(D, np.float32))
                    for _ in range(3)]
            for f in futs:
                np.testing.assert_allclose(
                    f.result(timeout=60), _expect(np.ones(D)),
                    rtol=1e-5, atol=1e-5)
            s = fe.stats()
        finally:
            fe.close()
        assert s["batches"] == 1 and s["completed"] == 3

    def test_no_recompile_across_mixed_shapes(self):
        """The no-recompile pin: after warmup the compile count equals
        the ladder's closed shape set and NO mix of request shapes
        grows it."""
        env = _base_env(None, HOROVOD_SERVING_MAX_LEN=32)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(1)
            want = len(fe.ladder.shapes((D,)))
            assert want == 6  # b in (1,2,4) x L in (16,32)
            deadline = time.monotonic() + 60
            while (fe.stats()["compiles"] < want
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert fe.stats()["compiles"] == want
            rng = np.random.RandomState(1)
            xs = [rng.randn(L, D).astype(np.float32)
                  for L in (3, 17, 32, 1, 9, 16, 31, 5)]
            futs = [fe.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                got = f.result(timeout=60)
                assert got.shape == x.shape  # unpadded to true length
                np.testing.assert_allclose(got, _expect(x),
                                           rtol=1e-5, atol=1e-5)
            s = fe.stats()
        finally:
            fe.close()
        assert s["compiles"] == want, \
            "a request shape escaped the bucket ladder"
        assert s["dropped"] == 0

    def test_submit_validates_shapes(self):
        env = _base_env(None, HOROVOD_SERVING_MAX_LEN=32)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            with pytest.raises(ValueError):
                fe.submit(np.ones((3, D + 1), np.float32))
            with pytest.raises(ServingError):
                fe.submit(np.ones((33, D), np.float32))  # > MAX_LEN
        finally:
            fe.close()
        fe2 = ServingFrontend(_forward, (D,), env=_base_env(),
                              start_pool=False, autoscale=False)
        try:
            with pytest.raises(ValueError):
                fe2.submit(np.ones(D + 1, np.float32))
        finally:
            fe2.close()

    def test_submit_after_close_fails_visibly(self):
        fe = ServingFrontend(_forward, (D,), env=_base_env(),
                             start_pool=False, autoscale=False)
        fe.close()
        with pytest.raises(ServingError):
            fe.submit(np.ones(D, np.float32))


# -- retry / exactly-once under injected faults ----------------------------


class TestRetryExactlyOnce:
    def test_injected_worker_death_retries_without_loss(self, tmp_path):
        """`serving.batch:error` kills a worker mid-batch: the batch
        must be re-dispatched on the survivor, every request must
        complete exactly once, and the retry must be journaled with
        its cause."""
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(2)
            faults.configure("serving.batch:error:at=2", seed=0)
            rng = np.random.RandomState(2)
            xs = [rng.randn(D).astype(np.float32) for _ in range(12)]
            futs = [fe.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                np.testing.assert_allclose(
                    f.result(timeout=60), _expect(x),
                    rtol=1e-5, atol=1e-5)
            faults.configure("", seed=0)
            s = fe.stats()
        finally:
            fe.close()
        assert s["completed"] == 12 and s["failed"] == 0
        assert s["dropped"] == 0
        assert s["retries"] >= 1
        evs = _journal_events(tmp_path)
        retried = [e for e in evs if e["type"] == "batch_retried"]
        assert retried and retried[0]["cause"] == "fault_error"
        assert retried[0]["attempt"] == 1
        deaths = [e for e in evs if e["type"] == "scale_event"
                  and e["reason"] == "worker_death:fault_error"]
        assert deaths and deaths[0]["worker"] == retried[0]["worker"]

    def test_hung_worker_deadline_and_duplicate_suppression(
            self, tmp_path):
        """`serving.batch:hang` parks a worker holding its batch: the
        per-batch deadline (the serving heartbeat detector) requeues
        it, and the revenant's late completion is suppressed by the
        exactly-once latch — counted, never double-delivered."""
        env = _base_env(tmp_path,
                        HOROVOD_SERVING_WORKER_TIMEOUT_S="0.4")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(2)
            faults.configure("serving.batch:hang:at=1", seed=0)
            xs = [np.full(D, i, np.float32) for i in range(4)]
            futs = [fe.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                np.testing.assert_allclose(
                    f.result(timeout=60), _expect(x),
                    rtol=1e-5, atol=1e-5)
            faults.configure("", seed=0)
            # The revenant wakes after ~4x the timeout and attempts
            # completion; wait for the latch to absorb all 4 rows.
            deadline = time.monotonic() + 15
            while (fe.stats()["duplicates_suppressed"] < 4
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            s = fe.stats()
        finally:
            fe.close()
        assert s["completed"] == 4 and s["dropped"] == 0
        assert s["retries"] >= 1
        assert s["duplicates_suppressed"] == 4
        retried = [e for e in _journal_events(tmp_path)
                   if e["type"] == "batch_retried"]
        assert retried and retried[0]["cause"] == "timeout"

    def test_retry_budget_exhausted_fails_visibly(self, tmp_path):
        """When every dispatch dies, the request must FAIL (visible
        ServingError, counted) rather than silently drop or hang."""
        env = _base_env(tmp_path,
                        HOROVOD_SERVING_RETRY_LIMIT="1",
                        HOROVOD_SERVING_SCALE_INTERVAL_S="0.02")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=True)
        try:
            fe.start_pool(1)
            faults.configure("serving.batch:error", seed=0)
            fut = fe.submit(np.ones(D, np.float32))
            with pytest.raises(ServingError, match="dispatch attempts"):
                fut.result(timeout=60)
            faults.configure("", seed=0)
            s = fe.stats()
        finally:
            faults.configure("", seed=0)
            fe.close()
        assert s["failed"] == 1 and s["completed"] == 0
        assert s["dropped"] == 0
        assert s["retries"] == 1  # limit=1: one requeue, then fail


# -- autoscaling -----------------------------------------------------------


class TestAutoscale:
    def test_scale_up_on_queue_depth_then_down_on_idle(self, tmp_path):
        env = _base_env(tmp_path,
                        HOROVOD_SERVING_MAX_BATCH="1",
                        HOROVOD_SERVING_LATENCY_BUDGET_MS="1",
                        HOROVOD_SERVING_MAX_WORKERS="3",
                        HOROVOD_SERVING_SCALE_INTERVAL_S="0.02",
                        HOROVOD_SERVING_SCALE_UP_QUEUE="1.0",
                        HOROVOD_SERVING_SCALE_DOWN_IDLE_S="0.25")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=True, autoscale=True)
        peak = 0
        try:
            # Slow every batch so the queue builds faster than one
            # worker drains it.
            faults.configure("serving.batch:delay:ms=30", seed=0)
            futs = [fe.submit(np.full(D, i, np.float32))
                    for i in range(30)]
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                peak = max(peak, fe.stats()["workers"])
                if peak >= 2 and all(f.done for f in futs):
                    break
                time.sleep(0.02)
            for f in futs:
                f.result(timeout=60)
            faults.configure("", seed=0)
            assert peak >= 2, "queue depth never scaled the pool out"
            # Idle: the pool must shrink back to the floor.
            deadline = time.monotonic() + 20
            while (fe.stats()["workers"] > 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            s = fe.stats()
        finally:
            faults.configure("", seed=0)
            fe.close()
        assert s["workers"] == 1
        assert s["dropped"] == 0
        dirs = [e["direction"] for e in _journal_events(tmp_path)
                if e["type"] == "scale_event"]
        assert "up" in dirs and "down" in dirs

    def test_floor_restored_after_worker_death(self, tmp_path):
        env = _base_env(tmp_path,
                        HOROVOD_SERVING_MIN_WORKERS="2",
                        HOROVOD_SERVING_SCALE_INTERVAL_S="0.02")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=True, autoscale=True)
        try:
            faults.configure("serving.batch:error:at=1", seed=0)
            fut = fe.submit(np.ones(D, np.float32))
            fut.result(timeout=60)
            faults.configure("", seed=0)
            deadline = time.monotonic() + 20
            while (fe.stats()["workers"] < 2
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            s = fe.stats()
        finally:
            faults.configure("", seed=0)
            fe.close()
        assert s["workers"] == 2, "autoscaler never restored the floor"
        reasons = [e["reason"] for e in _journal_events(tmp_path)
                   if e["type"] == "scale_event"]
        assert "floor" in reasons


# -- elastic membership hook -----------------------------------------------


class TestMembershipHook:
    def test_driver_listener_fires_and_is_contained(self):
        from horovod_tpu.runner.elastic import (ElasticDriver,
                                                FixedHosts)
        drv = ElasticDriver(["true"], FixedHosts("", 2))
        try:
            seen = []
            drv.add_membership_listener(
                lambda epoch, infos: seen.append(
                    (epoch, len(infos))))
            drv.add_membership_listener(
                lambda epoch, infos: 1 / 0)  # must be contained
            hosts = drv.discovery.find_available_hosts_and_slots()
            infos, _ = drv._publish_epoch(hosts)
            assert seen == [(1, len(infos))]
            drv._publish_epoch(hosts)
            assert seen[-1][0] == 2
        finally:
            drv.rendezvous.stop()

    def test_on_membership_resizes_pool(self, tmp_path):
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=True, autoscale=False)
        try:
            fe.on_membership(7, [object()] * 3)
            assert fe.stats()["workers"] == 3
            fe.on_membership(8, [object()] * 1)
            assert fe.stats()["workers"] == 1
            # clamped to the knob ceiling (MAX_WORKERS=4)
            fe.on_membership(9, [object()] * 9)
            assert fe.stats()["workers"] == 4
        finally:
            fe.close()
        evs = [e for e in _journal_events(tmp_path)
               if e["type"] == "scale_event"
               and e["reason"] == "membership"]
        assert [e["epoch"] for e in evs] == [7, 8, 9]
        assert [e["workers_to"] for e in evs] == [3, 1, 4]


# -- remote pool: real mid-batch process kill over the wire ----------------


def _spawn_remote_worker(tmp_path, port, secret, wid, extra_env=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SERVING_TEST_STANDALONE"] = "1"
    env["SERVING_TEST_ADDR"] = "127.0.0.1"
    env["SERVING_TEST_PORT"] = str(port)
    env["SERVING_TEST_SECRET"] = secret
    env["SERVING_TEST_DMODEL"] = str(D)
    env["SERVING_TEST_WID"] = wid
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable,
         os.path.join("tests", "serving_chaos_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.integration
def test_remote_worker_mid_batch_kill_zero_dropped(tmp_path):
    """Two real worker processes pull batches over the HMAC-signed
    wire; one is seeded to CRASH (os._exit) mid-batch. The dispatch
    deadline must requeue its in-flight batch on the survivor and
    every request must complete — zero dropped."""
    jdir = tmp_path / "journal"
    jdir.mkdir()
    env = _base_env(None, HOROVOD_SERVING_WORKER_TIMEOUT_S="1")
    env["HOROVOD_JOURNAL_DIR"] = str(jdir)
    fe = ServingFrontend(_forward, (D,), env=env,
                         start_pool=False, autoscale=False)
    procs = []
    try:
        port, secret = fe.serve_endpoint()
        wa = _spawn_remote_worker(
            tmp_path, port, secret, "wA",
            {"HOROVOD_FAULTS": "serving.batch:crash:at=2",
             "HOROVOD_FAULTS_SEED": "3",
             "HOROVOD_JOURNAL_DIR": str(jdir)})
        wb = _spawn_remote_worker(tmp_path, port, secret, "wB")
        procs = [wa, wb]
        rng = np.random.RandomState(4)
        xs = [rng.randn(D).astype(np.float32) for _ in range(24)]
        futs = []
        for x in xs:
            futs.append(fe.submit(x))
            time.sleep(0.02)
        for x, f in zip(xs, futs):
            np.testing.assert_allclose(
                f.result(timeout=120), _expect(x),
                rtol=1e-5, atol=1e-5)
        s = fe.stats()
        assert wa.wait(timeout=60) == 43, "wA should die on the seam"
    finally:
        fe.close()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert wb.returncode == 0, wb.stdout.read()
    assert s["completed"] == 24 and s["failed"] == 0
    assert s["dropped"] == 0
    assert s["retries"] >= 1
    retried = [e for e in _journal_events(tmp_path)
               if e["type"] == "batch_retried"]
    assert retried and retried[0]["cause"] == "timeout"
    assert retried[0]["worker"] == "wA"
    # the dead worker's own journal carries the fault attribution
    wa_events = _journal_events(tmp_path, role="serving-wA")
    fired = [e for e in wa_events if e["type"] == "fault_fired"]
    assert fired and fired[0]["point"] == "serving.batch"
    assert fired[0]["action"] == "crash"


# -- committed bench artifact pins -----------------------------------------


class TestServingBenchArtifact:
    def test_artifact_pins(self):
        doc = json.load(open(BENCH_ARTIFACT))
        # the measured numbers are tied to the exact executable-shape
        # set via the ladder digest — same derivation here must match
        assert doc["ladder"]["digest"] == build_ladder(
            doc["config"]["max_batch"], 0).digest
        # acceptance bar: the injected mid-batch worker death lost
        # nothing, and the recovery went through the retry path
        assert doc["retry"]["dropped"] == 0
        assert doc["retry"]["failed"] == 0
        assert doc["retry"]["retries"] >= 1
        assert sorted(doc["latency_vs_qps"]) == \
            ["qps100", "qps200", "qps50"]
        for leg in doc["latency_vs_qps"].values():
            assert 0 < leg["p50_ms"] <= leg["p99_ms"]
        assert sorted(doc["scaleout"]) == \
            ["workers1", "workers2", "workers4"]
        for leg in doc["scaleout"].values():
            assert leg["achieved_qps"] > 0


# -- probe-gated 2-rank chaos run through the elastic runner ---------------


@pytest.mark.integration
def test_two_rank_pool_chaos_zero_dropped(tmp_path,
                                          multiproc_data_plane):
    """The acceptance chaos leg: a 2-rank elastic-runner gang joins
    the frontend's pool; rank 1 is seeded to crash mid-batch (once,
    latched across the gang restart). The frontend — which outlives
    the gang, as a serving driver does — must retry on survivors and
    complete every request, and the incident report must attribute
    the recovery to the injected seam."""
    jdir = tmp_path / "journal"
    jdir.mkdir()
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho localhost:2\n")
    script.chmod(0o755)

    senv = _base_env(None, HOROVOD_SERVING_WORKER_TIMEOUT_S="2")
    senv["HOROVOD_JOURNAL_DIR"] = str(jdir)
    fe = ServingFrontend(_forward, (D,), env=senv,
                         start_pool=False, autoscale=False)
    p = None
    try:
        port, secret = fe.serve_endpoint()
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["SERVING_TEST_ADDR"] = "127.0.0.1"
        env["SERVING_TEST_PORT"] = str(port)
        env["SERVING_TEST_SECRET"] = secret
        env["SERVING_TEST_DMODEL"] = str(D)
        env["HOROVOD_JOURNAL_DIR"] = str(jdir)
        env["HOROVOD_FAULTS"] = (
            f"serving.batch:crash:at=3,rank=1,"
            f"once={tmp_path / 'crash.latch'}")
        env["HOROVOD_FAULTS_SEED"] = "7"
        env["HOROVOD_ELASTIC_TEARDOWN_GRACE"] = "3"
        p = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.runner",
             "--host-discovery-script", str(script),
             "--min-num-proc", "2",
             "--host-change-detection-interval", "0.5",
             "--reset-limit", "3",
             sys.executable,
             os.path.join("tests", "serving_chaos_worker.py")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        rng = np.random.RandomState(5)
        xs = [rng.randn(D).astype(np.float32) for _ in range(60)]
        futs = []
        for x in xs:
            futs.append(fe.submit(x))
            time.sleep(0.05)
        for x, f in zip(xs, futs):
            np.testing.assert_allclose(
                f.result(timeout=300), _expect(x),
                rtol=1e-5, atol=1e-5)
        s = fe.stats()
    finally:
        fe.close()
        if p is not None:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
    assert p.returncode == 0, out
    assert s["completed"] == 60 and s["failed"] == 0
    assert s["dropped"] == 0
    assert s["retries"] >= 1
    retried = [e for e in _journal_events(tmp_path)
               if e["type"] == "batch_retried"]
    assert retried, "mid-batch crash must journal the retry"
    report = journal.incident_report(str(jdir))
    assert report["summary"]["recoveries"] >= 1
    rec = report["recoveries"][0]
    assert rec["cause"]["rank"] == 1, rec
    assert rec["cause"]["kind"] == "crash"
    assert rec["cause"]["seam"] == "serving.batch:crash"
