"""Worker for the 4-rank hierarchical-control-plane wiring test:
HOROVOD_CONTROL_TREE_ARITY=2 over 4 ranks places rank 2 UNDER the
rank-1 aggregator (tier 2), so every negotiated op crosses a real
two-hop aggregation path. The ops here are negotiation-level only
(generic entries with per-rank metadata) — no cross-process XLA data
plane, so the test runs on jaxlibs whose CPU backend cannot (the same
gate every mp data-plane test skips on)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["HOROVOD_CONTROL_TREE_ARITY"] = "2"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402
from horovod_tpu.core import native  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 4, f"test expects 4 ranks, got {n}"

    ctl = state().engine.controller
    assert ctl is not None, "negotiated controller required"
    from horovod_tpu.core.native import NativeCore
    assert isinstance(ctl.core, NativeCore), type(ctl.core)

    # The wiring must agree with the C++ placement arithmetic.
    want_tier = native.tree_tier(r, n, 2)
    assert ctl.core.tree_tier() == want_tier, \
        (r, ctl.core.tree_tier(), want_tier)
    # With (size=4, arity=2) rank 2 hangs under the rank-1
    # aggregator: the tree is genuinely deeper than the flat star.
    assert native.tree_depth(n, 2) == 2
    if r == 2:
        assert want_tier == 2, want_tier
        assert native.tree_parent(r, n, 2) == 1

    # Several rounds of negotiated generic ops with per-rank
    # metadata: the metas must come back ';'-aggregated by WORLD rank
    # on every rank — rank 2's meta crossed the aggregator hop both
    # ways, and steady-state rounds ride the response-cache-free
    # generic path.
    for step in range(5):
        got = {}

        def record(metas, step=step, got=got):
            got["metas"] = metas
            return None

        h = ctl.submit_generic(f"tree_meta_{step}", 4, record,
                               meta=f"r{r}s{step}")
        hvd.synchronize(h.id)
        assert got["metas"] == [f"r{i}s{step}" for i in range(n)], \
            got["metas"]

    # The tier gauge is visible in the metrics snapshot.
    snap = hvd.metrics()
    assert snap["hvd_control_tree_depth"][()] == float(want_tier), \
        snap["hvd_control_tree_depth"]
    # Rounds were observed.
    rounds = snap["hvd_control_round_seconds"][()]
    assert rounds["count"] >= 5, rounds

    hvd.shutdown()
    print(f"TREE WIRE OK rank={r} tier={want_tier}", flush=True)


main()
