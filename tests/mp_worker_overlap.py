"""Worker for the 2-rank jit-overlap merged-timeline test: builds the
bucketed train step over a mesh spanning BOTH processes' devices with
a tracing.OverlapProbe attached, runs one unrecorded compile step
(compile cycles excluded from the artifact), then records a few
measured steps — per-bucket REDUCE spans land on this rank's timeline
lanes inside STEP envelopes, merged afterwards by the test with
tracing.merge into the cross-rank artifact."""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# OVERLAP_WORKER_LOCAL_MESH=1: each rank runs the bucketed step over
# its OWN 8-virtual-device mesh instead of the cross-process global
# mesh — for jaxlibs whose CPU backend cannot run multiprocess
# computations (the data plane of the global mesh). Everything else —
# two real processes, per-rank timelines, control-plane clock
# calibration, the merge — is the real path; the committed
# benchmarks/TIMELINE_overlap_2proc_r06.json artifact records which
# mode produced it.
_LOCAL_MESH = os.environ.get("OVERLAP_WORKER_LOCAL_MESH") == "1"
if _LOCAL_MESH:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device"
                                 "_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import tracing  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402
from horovod_tpu.parallel import build_train_step  # noqa: E402
from horovod_tpu.parallel.mesh import data_parallel_mesh  # noqa: E402
from horovod_tpu.parallel.train import last_overlap_info  # noqa: E402
from horovod_tpu.timeline import Timeline  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n
    if _LOCAL_MESH:
        mesh = data_parallel_mesh(jax.local_devices())
        assert mesh.devices.size == 8, mesh
    else:
        mesh = data_parallel_mesh()
        assert mesh.devices.size == 2, mesh

    def loss_fn(params, batch):
        h = jnp.tanh(batch[:, None] * params["w1"][None, :])
        h = h @ params["w2"]
        return jnp.mean((h * params["w3"][None, :]) ** 2)

    params = {"w1": jnp.arange(64.0) / 64.0,
              "w2": jnp.ones((64, 32)) * 0.1,
              "w3": jnp.ones(32)}
    opt = optax.sgd(0.01)
    opt_state = opt.init(params)

    probe = tracing.OverlapProbe()
    # Threshold sized so w2 (8 KiB f64 / 4 KiB f32) splits from the
    # small vectors: >= 2 buckets, reverse order (w3's bucket first).
    step = build_train_step(loss_fn, opt, mesh, donate=False,
                            overlap=True, overlap_threshold=2048,
                            overlap_probe=probe)
    batch_host = np.arange(16.0, dtype=np.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    batch = jax.device_put(
        jnp.asarray(batch_host), NamedSharding(mesh, P("data")))
    jax.block_until_ready(batch)

    out = step(params, opt_state, batch)      # compile: unrecorded
    jax.block_until_ready(out)
    info = last_overlap_info()
    assert info["enabled"] and info["buckets"] >= 2, info
    assert probe.spans() == []                # disarmed => no spans

    probe.armed = True
    for s in range(4):
        tracing.set_step(s)
        t0 = time.monotonic_ns()
        params, opt_state, metrics = step(params, opt_state, batch)
        jax.block_until_ready(metrics["loss"])
        probe.step_span(t0, time.monotonic_ns())
    probe.armed = False

    spans = probe.spans()
    assert len(spans) >= 4 * info["buckets"], (len(spans), info)
    acct = probe.hidden_fraction()
    assert acct["spans"] == len(spans)

    tl = state().timeline
    assert tl is not None, "worker needs HOROVOD_TIMELINE set"
    wrote = probe.to_timeline(tl)
    assert wrote == len(spans)
    if not _LOCAL_MESH:
        # One negotiated eager collective per rank keeps the merge's
        # cross-rank span machinery engaged alongside the overlap
        # lanes (needs the cross-process data plane, absent in
        # local-mesh mode).
        hvd.allreduce(jnp.ones(8, jnp.float32), op=hvd.Sum,
                      name="overlap_sentinel")
        hvd.barrier()
    path = Timeline.rank_path(os.environ["HOROVOD_TIMELINE"], r)
    assert os.path.exists(path), path
    hvd.shutdown()
    print(f"OVERLAP WORKER OK rank={r} buckets={info['buckets']} "
          f"spans={len(spans)} "
          f"exposed={acct['exposed_comm_fraction']}", flush=True)


main()
