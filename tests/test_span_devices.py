"""The eager data plane must span every local device (round-3 verdict
Missing #1): multi-process runs where each process owns SEVERAL
devices — the CPU stand-in for multi-chip TPU hosts — plus the
launcher's per-chip pinning env (tested as string construction, the
reference's own launcher test technique, SURVEY.md §4 item 4)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.integration
@pytest.mark.parametrize("np_,devs", [(2, 2), (3, 2), (8, 2)])
def test_eager_span_devices(np_, devs, multiproc_data_plane):
    """`hvd.allreduce` reduces over (processes x local devices): the
    wide mesh covers every device and the summed payload is exact."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         sys.executable, os.path.join("tests", "mp_worker_span.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("SPAN ALL OK") == np_


@pytest.mark.integration
def test_hierarchical_composes_with_devices():
    """HOROVOD_HIERARCHICAL_ALLREDUCE on multi-chip processes takes
    the ('cross','local','dev') composed path — every chip busy, DCN
    phase moving 1/(local*dev) of the bytes (round-4 verdict Missing
    #2)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "4",
         sys.executable, os.path.join("tests", "mp_worker_hier.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("HIER ALL OK") == 4


class TestPerChipLaunchEnv:
    """Per-chip launch mode: the launcher pins one chip per slot so
    rank == accelerator, the reference's contract (SURVEY.md §0,
    hard-part #4). No TPU hosts in CI — assert the env construction."""

    def make_infos(self, hosts, np_):
        from horovod_tpu.runner.hosts import assign_ranks, parse_hosts
        return assign_ranks(parse_hosts(hosts, np_), np_)

    def test_single_host_four_chips(self):
        from horovod_tpu.runner.hosts import per_chip_env
        infos = self.make_infos("localhost:4", 4)
        env = per_chip_env(infos[1], infos)
        assert env["TPU_VISIBLE_CHIPS"] == "1"
        assert env["TPU_VISIBLE_DEVICES"] == "1"
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
        assert env["TPU_PROCESS_BOUNDS"] == "2,2,1"
        assert env["CLOUD_TPU_TASK_ID"] == "1"
        assert env["TPU_PROCESS_PORT"] == "8477"  # base + local_rank
        assert env["TPU_PROCESS_ADDRESSES"] == (
            "localhost:8476,localhost:8477,"
            "localhost:8478,localhost:8479")

    def test_two_hosts_eight_chips(self):
        from horovod_tpu.runner.hosts import per_chip_env
        infos = self.make_infos("h1:4,h2:4", 8)
        env = per_chip_env(infos[5], infos)  # rank 5 = h2 slot 1
        assert env["TPU_VISIBLE_CHIPS"] == "1"
        assert env["CLOUD_TPU_TASK_ID"] == "5"
        assert env["TPU_PROCESS_BOUNDS"] == "2,4,1"
        assert env["TPU_PROCESS_ADDRESSES"] == (
            "h1:8476,h1:8477,h1:8478,h1:8479,"
            "h2:8476,h2:8477,h2:8478,h2:8479")
        assert env["TPU_PROCESS_PORT"] == "8477"

    def test_bounds_override(self):
        from horovod_tpu.runner.hosts import per_chip_env
        infos = self.make_infos("localhost:4", 4)
        env = per_chip_env(infos[0], infos,
                           process_bounds="4,1,1",
                           chips_per_process_bounds="1,1,1")
        assert env["TPU_PROCESS_BOUNDS"] == "4,1,1"

    def test_launcher_flag_injects_env(self):
        """--per-chip threads the pinning env into each child's env."""
        from horovod_tpu.runner import launch
        from horovod_tpu.runner.hosts import assign_ranks, parse_hosts
        infos = assign_ranks(parse_hosts("localhost:2", 2), 2)
        env = launch.build_env(infos[1], "localhost:1234",
                               base_env={}, per_chip=True,
                               all_infos=infos)
        assert env["TPU_VISIBLE_CHIPS"] == "1"
        assert env["HOROVOD_RANK"] == "1"
        # without the flag, no TPU pinning vars appear
        env2 = launch.build_env(infos[1], "localhost:1234", base_env={})
        assert "TPU_VISIBLE_CHIPS" not in env2
