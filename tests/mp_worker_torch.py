"""Worker for the torch-frontend launcher test: exercises
`import horovod_tpu.torch as hvd` across REAL processes (the
reference analog: horovodrun -np 2 pytest test_torch.py,
SURVEY.md §4 tier 1)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    print(f"torch worker rank={r} size={n}")

    # allreduce average of rank-dependent tensors
    out = hvd.allreduce(torch.full((4,), float(r + 1)), name="t0")
    np.testing.assert_allclose(out.numpy(),
                               np.full(4, sum(range(1, n + 1)) / n))

    # in-place sum
    t = torch.full((3,), float(r))
    hvd.allreduce_(t, op=hvd.Sum, name="t1")
    np.testing.assert_allclose(t.numpy(), np.full(3, sum(range(n))))

    # bf16 wire, dtype preserved
    out = hvd.allreduce(torch.ones(8, dtype=torch.bfloat16),
                        op=hvd.Sum, name="t2")
    assert out.dtype == torch.bfloat16
    np.testing.assert_allclose(out.float().numpy(), float(n))

    # uneven allgather
    out = hvd.allgather(torch.full((r + 1, 2), float(r)), name="t3")
    want = np.concatenate(
        [np.full((i + 1, 2), float(i)) for i in range(n)])
    np.testing.assert_allclose(out.numpy(), want)

    # broadcast_parameters: every rank converges to rank 0's weights
    torch.manual_seed(100 + r)   # deliberately different per rank
    model = torch.nn.Linear(3, 2)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    gathered = hvd.allgather(model.weight.detach().reshape(1, -1),
                             name="t4")
    for i in range(1, n):
        np.testing.assert_allclose(gathered[i].numpy(),
                                   gathered[0].numpy())

    # hook-based DistributedOptimizer: rank-dependent batches, grads
    # averaged across ranks => identical post-step weights everywhere
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.5),
        named_parameters=model.named_parameters())
    X = torch.full((8, 3), float(r + 1))
    Y = torch.zeros(8, 2)
    for _ in range(3):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(X), Y)
        loss.backward()
        opt.step()
    gathered = hvd.allgather(model.weight.detach().reshape(1, -1),
                             name="t5")
    for i in range(1, n):
        np.testing.assert_allclose(gathered[i].numpy(),
                                   gathered[0].numpy(), rtol=1e-6)

    # sparse allreduce over torch sparse COO (rank-dependent nnz)
    if r == 0:
        s = torch.sparse_coo_tensor(torch.zeros((1, 0), dtype=torch.long),
                                    torch.zeros((0, 2)), size=(5, 2))
    else:
        s = torch.sparse_coo_tensor(
            torch.tensor([[1, min(r + 1, 4)]]),
            torch.full((2, 2), float(r)), size=(5, 2))
    out = hvd.sparse_allreduce(s, op=hvd.Sum, name="t6").to_dense()
    want = np.zeros((5, 2))
    for rr in range(1, n):
        want[1] += rr
        want[min(rr + 1, 4)] += rr
    np.testing.assert_allclose(out.numpy(), want)

    # optimizer-state broadcast after real steps
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    # ASYMMETRIC optimizer state: root resumed (materialized Adam
    # state), workers fresh (state == {}) — the checkpoint-resume
    # case. Root's manifest drives the broadcast set, so this must
    # not deadlock, and workers must receive root's moments.
    model2 = torch.nn.Linear(2, 2)
    hvd.broadcast_parameters(model2.state_dict(), root_rank=0)
    opt2 = torch.optim.Adam(model2.parameters(), lr=0.01)
    if r == 0:
        torch.nn.functional.mse_loss(model2(torch.ones(4, 2)),
                                     torch.zeros(4, 2)).backward()
        opt2.step()
    hvd.broadcast_optimizer_state(opt2, root_rank=0)
    st2 = opt2.state_dict()["state"]
    assert st2, f"rank {r}: optimizer state empty after broadcast"
    ea = next(iter(st2.values()))["exp_avg"].reshape(1, -1)
    gathered = hvd.allgather(ea, name="t7")
    for i in range(1, n):
        np.testing.assert_allclose(gathered[i].numpy(),
                                   gathered[0].numpy())

    # dtype x op matrix through the bridge (reference analog:
    # test_torch.py's exhaustive dtype/op coverage under -np 2).
    vals = [i + 2 for i in range(n)]
    for dt in [torch.float32, torch.float16, torch.bfloat16,
               torch.int32, torch.uint8]:
        is_float = dt.is_floating_point
        ops = [(hvd.Sum, float(sum(vals))),
               (hvd.Min, float(min(vals))),
               (hvd.Max, float(max(vals))),
               (hvd.Product, float(np.prod(vals)))]
        if is_float:
            ops.append((hvd.Average, sum(vals) / n))
        for op_, want in ops:
            x = torch.full((4, 3), r + 2).to(dt)
            out = hvd.allreduce(x, op=op_, name=f"mx.{dt}.{op_}")
            assert out.dtype == dt, (out.dtype, dt)
            tol = 5e-2 if dt in (torch.bfloat16, torch.float16) else 1e-6
            np.testing.assert_allclose(
                out.to(torch.float64).numpy(), np.full((4, 3), want),
                rtol=tol)

    # SyncBatchNorm oracle: each rank holds a DIFFERENT shard (uneven
    # sizes!) of a global batch; sync-BN output + input grad on the
    # shard must equal vanilla BatchNorm run on the concatenated
    # batch (reference: test_torch.py's sync BN coverage).
    torch.manual_seed(7)
    full = torch.randn(2 * n + n * (n + 1) // 2, 3, 4)
    shard_sizes = [2 + i + 1 for i in range(n)]
    off = sum(shard_sizes[:r])
    mine = full[off:off + shard_sizes[r]].clone().requires_grad_(True)
    bn = hvd.SyncBatchNorm(3, momentum=0.2)
    y = bn(mine)
    y.sum().backward()

    ref = torch.nn.BatchNorm1d(3, momentum=0.2)
    xref = full.clone().requires_grad_(True)
    yref = ref(xref)
    yref.sum().backward()
    np.testing.assert_allclose(
        y.detach().numpy(),
        yref[off:off + shard_sizes[r]].detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(
        mine.grad.numpy(),
        xref.grad[off:off + shard_sizes[r]].numpy(), atol=1e-5)
    np.testing.assert_allclose(bn.running_mean.numpy(),
                               ref.running_mean.numpy(), atol=1e-6)
    np.testing.assert_allclose(bn.running_var.numpy(),
                               ref.running_var.numpy(), atol=1e-5)
    # weight grad: LOCAL here; averaged by the optimizer like any
    # other param grad. Allreduce(Sum) of local == the oracle's.
    wg = hvd.allreduce(bn.weight.grad, op=hvd.Sum, name="t8")
    np.testing.assert_allclose(wg.numpy(), ref.weight.grad.numpy(),
                               atol=1e-4)

    hvd.barrier()
    print(f"rank {r}: TORCH FRONTEND ALL OK")
    hvd.shutdown()


if __name__ == "__main__":
    main()
