"""Pallas kernels, run in interpreter mode on the CPU mesh
(tests/conftest.py) and cross-checked against the jnp math and the
numpy model. Reference anchor for the op they implement:
horovod/common/ops/adasum/adasum.h (ComputeDotAndNormSqrds +
ScaledAdd)."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.adasum import adasum_reference
from horovod_tpu.ops.pallas_kernels import (BLOCK_ROWS, LANES,
                                            adasum_pair_combine)


def _np_combine(a, b):
    return adasum_reference([np.asarray(a, np.float64),
                             np.asarray(b, np.float64)])


@pytest.mark.parametrize("n", [
    1,                       # scalar-ish, full padding
    100,                     # sub-lane
    LANES * 8,               # exactly one f32 tile
    BLOCK_ROWS * LANES,      # exactly one block
    BLOCK_ROWS * LANES + 7,  # crosses a block boundary
    3 * BLOCK_ROWS * LANES,  # multi-block grid
])
def test_pair_combine_matches_numpy(n):
    rng = np.random.default_rng(n)
    a = rng.standard_normal(n).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    got = adasum_pair_combine(jnp.asarray(a), jnp.asarray(b),
                              interpret=True)
    want = _np_combine(a, b)
    np.testing.assert_allclose(np.asarray(got), want,
                               rtol=1e-5, atol=1e-5)


def test_pair_combine_shapes_preserved():
    a = jnp.ones((4, 33, 7), jnp.float32)
    b = jnp.full((4, 33, 7), 2.0, jnp.float32)
    out = adasum_pair_combine(a, b, interpret=True)
    assert out.shape == (4, 33, 7) and out.dtype == jnp.float32


def test_pair_combine_zero_norm_guard():
    z = jnp.zeros(256, jnp.float32)
    v = jnp.ones(256, jnp.float32)
    out = adasum_pair_combine(z, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.ones(256), rtol=1e-6)


def test_pair_combine_orthogonal_is_sum():
    a = np.zeros(512, np.float32)
    b = np.zeros(512, np.float32)
    a[:256] = 1.0
    b[256:] = 1.0
    out = adasum_pair_combine(jnp.asarray(a), jnp.asarray(b),
                              interpret=True)
    np.testing.assert_allclose(np.asarray(out), a + b, rtol=1e-6)


def test_bf16_inputs_accumulate_in_f32():
    rng = np.random.default_rng(0)
    a = rng.standard_normal(5000).astype(np.float32)
    b = rng.standard_normal(5000).astype(np.float32)
    out = adasum_pair_combine(jnp.asarray(a, jnp.bfloat16),
                              jnp.asarray(b, jnp.bfloat16),
                              interpret=True)
    assert out.dtype == jnp.bfloat16
    want = _np_combine(a, b)
    np.testing.assert_allclose(np.asarray(out, np.float32), want,
                               rtol=0.05, atol=0.05)


def test_forced_pallas_path_in_adasum_allreduce():
    """HOROVOD_ADASUM_PALLAS=1 (via config_overrides, the public way)
    routes the Adasum op through the kernel — interpreter here — and
    the kernel choice is part of the trace-cache key, so this init's
    setting cannot reuse a kernel traced with the other choice."""
    import horovod_tpu as hvd
    from horovod_tpu.ops import adasum as adasum_mod
    hvd.init(config_overrides={"HOROVOD_ADASUM_PALLAS": "1"})
    try:
        assert adasum_mod._use_pallas() is True
        x = jnp.asarray(np.arange(1000, dtype=np.float32))
        out = hvd.allreduce(x, op=hvd.Adasum, name="pallas_adasum")
        # single process: Adasum of one contribution is identity
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    finally:
        hvd.shutdown()


def test_adasum_kernel_cache_keyed_on_pallas_choice(hvd_single):
    """Same mesh/sig with a different use_pallas flag must be a
    distinct compiled kernel, not a cache hit."""
    from horovod_tpu.common.basics import _require_init
    from horovod_tpu.ops import adasum as adasum_mod
    from horovod_tpu.ops import dispatch
    pset = _require_init().process_set_table.global_set
    sig = dispatch._sig([jnp.ones(8)])
    k_off = adasum_mod._adasum_kernel(pset.mesh, 2, sig, False)
    k_on = adasum_mod._adasum_kernel(pset.mesh, 2, sig, True)
    assert k_off is not k_on
