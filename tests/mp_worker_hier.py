"""Worker for hierarchical x device-spanning composition (round-4
verdict Missing #2): 4 processes x 2 virtual devices each, with the
topology env faked to 2 "hosts" x 2 processes — so
HOROVOD_HIERARCHICAL_ALLREDUCE factors the world as
('cross'=2, 'local'=2, 'dev'=2) and an eager allreduce must take the
hier_wide path (every chip busy, DCN phase moving 1/(local*dev) of
the bytes), not idle the second chip like the 2-axis hier mesh did."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")

rank = int(os.environ.get("HOROVOD_RANK", "0"))
# Fake a 2-host x 2-proc topology (the launcher put all 4 on this
# host; slice-alignment needs local_size < world size).
os.environ["HOROVOD_LOCAL_SIZE"] = "2"
os.environ["HOROVOD_LOCAL_RANK"] = str(rank % 2)
os.environ["HOROVOD_CROSS_SIZE"] = "2"
os.environ["HOROVOD_CROSS_RANK"] = str(rank // 2)
os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.ops import dispatch  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 4, f"test expects 4 ranks, got {n}"
    ndev = len(jax.local_devices())
    assert ndev == 2, ndev

    # 1) big allreduce: hierarchical AND device-spanning.
    elems = 8192
    x = jnp.arange(elems, dtype=jnp.float32) + float(r)
    out = hvd.allreduce(x, name="hier_sum", op=hvd.Sum)
    info = dispatch.last_allreduce_info()
    assert info.get("path") == "hier_wide", info
    assert info.get("mesh_shape") == {"cross": 2, "local": 2,
                                      "dev": 2}, info
    expect = np.arange(elems, dtype=np.float32) * n + sum(range(n))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)
    print(f"rank {r}: hier_wide allreduce OK ({info})")

    # 2) grouped + fp16 wire through the same composed program.
    xs = [jnp.full((2048,), float(i + 1 + r), jnp.float32)
          for i in range(3)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Average,
                                 compression=hvd.Compression.fp16)
    assert dispatch.last_allreduce_info().get("path") == "hier_wide"
    for i, o in enumerate(outs):
        assert o.dtype == jnp.float32
        want = sum(float(i + 1 + rr) for rr in range(n)) / n
        np.testing.assert_allclose(np.asarray(o),
                                   np.full(2048, want), rtol=1e-2)
    print(f"rank {r}: hier_wide grouped+fp16 OK")

    # 2b) allgather composes too: ragged rows through the
    # ('cross','local','dev') staged gather.
    rows_mine = 512 + 16 * r
    out = hvd.allgather(jnp.full((rows_mine, 4), float(r), jnp.float32),
                        name="hier_ag")
    info = dispatch.last_op_info("allgather")
    assert info.get("path") == "hier_wide", info
    assert info.get("mesh_shape") == {"cross": 2, "local": 2,
                                      "dev": 2}, info
    off = 0
    for rr in range(n):
        seg = np.asarray(out[off:off + 512 + 16 * rr])
        np.testing.assert_allclose(seg, np.full(seg.shape, float(rr)))
        off += 512 + 16 * rr
    print(f"rank {r}: hier_wide allgather OK ({info})")

    # 3) span knob off -> the 2-axis hier path (representative chips).
    dispatch.set_span_devices("0")
    out = hvd.allreduce(jnp.full((8192,), 1.0, jnp.float32),
                        name="hier_narrow", op=hvd.Sum)
    info = dispatch.last_allreduce_info()
    assert info.get("path") == "hier", info
    np.testing.assert_allclose(np.asarray(out), np.full(8192, float(n)))
    dispatch.set_span_devices("auto")
    print(f"rank {r}: hier narrow fallback OK")

    hvd.shutdown()
    print(f"rank {r}: HIER ALL OK")


if __name__ == "__main__":
    main()
