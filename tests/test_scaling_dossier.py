"""Round-9 committed-artifact consistency: the scaling dossier
(benchmarks/SCALING_projection_r09.json) and the steady-state
composed timeline (benchmarks/TIMELINE_steady_2proc_r09.json) are
CLAIMS the repo ships — these tests keep them honest against drift:
every assumption source named in the dossier must exist, the
projection must still follow from its own stated inputs, and the
dossier must regenerate byte-identically from `bench.py
--scaling-report` (no silent hand edits). Since round 13 the command
emits SCALING_projection_r13.json (the r09 projection plus the
compression lever), so the byte-identity pin targets that file; the
r09 dossier stays committed as a cited historical input and keeps
its own consistency pins here."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOSSIER = os.path.join(REPO, "benchmarks",
                       "SCALING_projection_r09.json")
DOSSIER_R13 = os.path.join(REPO, "benchmarks",
                           "SCALING_projection_r13.json")
STEADY = os.path.join(REPO, "benchmarks",
                      "TIMELINE_steady_2proc_r09.json")


@pytest.fixture(scope="module")
def dossier():
    with open(DOSSIER) as f:
        return json.load(f)


def test_every_assumption_source_exists(dossier):
    """The falsifiability contract rests on traceability: each
    sourced assumption and rate names a committed artifact — a
    renamed or deleted artifact must fail loudly here, not rot the
    dossier."""
    paths = []
    for block in dossier["assumptions"].values():
        src = block.get("source", "")
        if ":" in src and "/" in src.split(":")[0]:
            paths.append(src.split(":")[0])
    for m in dossier["projection"].values():
        paths.append(m["rate_source"].split(":")[0])
    sub = dossier["assumptions"]["control_plane"]
    paths.append(sub["steady_negotiate_p50_ms"]["source"].split(":")[0])
    assert paths, "dossier names no sources at all?"
    for p in set(paths):
        assert os.path.exists(os.path.join(REPO, p)), \
            f"dossier cites missing artifact {p}"


def test_projection_follows_from_stated_inputs(dossier):
    """Recompute one curve point from the dossier's OWN stated
    method and inputs; a drift between the formulas documented and
    the numbers committed is a lying artifact."""
    a = dossier["assumptions"]
    eff_bw = (a["ici_gbps_per_chip"]["value"] / 8 * 1e9 *
              a["ici_utilization"]["value"])
    h = a["overlap_hidden_schedule_fraction"]["value"]
    bwd = a["backward_window_fraction"]["value"]
    for name, m in dossier["projection"].items():
        step = m["step_time_ms_1chip"] / 1e3
        for n_s, row in m["curve"].items():
            n = int(n_s)
            t_wire = m["wire_bytes_per_step"] * 2 * (n - 1) / n / eff_bw
            hidden = min(h * t_wire, bwd * step)
            eff = step / (step + (t_wire - hidden))
            assert abs(eff - row["efficiency"]) < 5e-4, (name, n_s)
            floor = step / (step + t_wire)
            assert abs(floor -
                       row["efficiency_no_overlap_floor"]) < 5e-4, \
                (name, n_s)


def test_headline_claim_holds(dossier):
    """>=90% at 32 chips for all three models, even at the
    zero-overlap floor — the dossier's headline, asserted from its
    own numbers."""
    floors = dossier["headline"]["no_overlap_floor_32chip"]
    assert set(floors) == {"resnet50", "vgg16",
                           "flagship_transformer"}
    for model, floor in floors.items():
        assert floor >= 0.90, (model, floor)


@pytest.mark.integration
def test_dossier_regenerates_byte_identical(tmp_path):
    """`bench.py --scaling-report` is pure arithmetic over committed
    inputs (eval_shape wire bytes, artifact reads — no timestamps,
    no randomness), so regeneration must reproduce the committed
    dossier EXACTLY; a mismatch means either a hand edit or an
    input drifted without re-emitting. Target is the CURRENT
    emission (r13, projection + compression lever); purity includes
    host device count — the lever's plan accounting runs on an
    AbstractMesh, so a 1-device host must reproduce the same
    bytes."""
    out = tmp_path / "dossier.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env.pop("BENCH_ICI_GBPS", None)
    env.pop("BENCH_ICI_UTILIZATION", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SCALING_OUT"] = str(out)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--scaling-report"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert out.read_bytes() == open(DOSSIER_R13, "rb").read(), \
        "regenerated dossier differs from the committed one"


def test_steady_timeline_claims():
    """The round-9 steady-state composed artifact's headline
    (VERDICT 'What's missing' 1): NEGOTIATE p50 below the cycle
    budget once the compile cycle is excluded, both ranks present,
    provenance stated."""
    with open(STEADY) as f:
        doc = json.load(f)
    neg = doc["metadata"]["negotiate_ms"]
    assert neg["steady_p50"] < neg["cycle_budget_ms"]
    assert neg["steady_p95"] < neg["cycle_budget_ms"]
    prov = doc["metadata"]["provenance"]
    assert prov["compile_cycles_excluded"] == [0]
    assert doc["metadata"]["ranks"] == [0, 1]
    # The spans the claim is computed from are really in the trace.
    neg_ends = [e for e in doc["traceEvents"]
                if e.get("name") == "NEGOTIATE"
                and e.get("ph") == "E"
                and "coordinator_negotiate_us" in e.get("args", {})]
    steady = [e for e in neg_ends if e["args"].get("step", 0) > 0]
    assert len(steady) >= neg["steady_count"] // 2
