"""Control-plane scale stress: the coordinator must absorb a pod-scale
connect storm and keep per-cycle agreement latency bounded well beyond
the 2-4 process integration tests (reference:
horovod/common/gloo/gloo_controller.cc leans on gloo's rendezvous and
tree broadcast for this property; this build's TCP coordinator has to
earn it explicitly — concurrent per-connection handshake threads, see
core/cc/controller.cc ServerAcceptLoop/HandshakeConn).

Runs the stress_scale binary (N in-process controllers over loopback)
at 32 and 64 workers and asserts:
  * every handshake of a CONCURRENT storm completes, fast;
  * agreement still reaches every rank in the same order (the binary
    exits non-zero on divergence);
  * steady-state agreement latency stays bounded.
Bounds are deliberately loose: CI hosts (this image exposes a single
CPU core to ~2N threads) measure scheduling noise, not the protocol.
The recorded curve for THIS host lives in benchmarks/
control_plane_scale.md.
"""

import json
import os
import shutil
import subprocess

import pytest

CCDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core", "cc")


def _build(target: str) -> None:
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, target],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]


def _run(workers: int, rounds: int = 15, tensors: int = 8,
         extra: tuple = ()) -> dict:
    r = subprocess.run(
        [os.path.join(CCDIR, "stress_scale"), str(workers),
         str(rounds), str(tensors), *extra],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.integration
def test_control_plane_scales_to_64_workers():
    _build("stress_scale")
    for workers in (32, 64):
        rec = _run(workers)
        # Concurrent connect storm: N-1 simultaneous mutual
        # challenge-response handshakes, all through one coordinator.
        assert rec["connect_s"] < 30.0, rec
        # Steady-state agreement: every rank sees every batch within
        # a loose bound (single-core CI scheduling noise included).
        assert rec["round_p95_ms"] < 2000.0, rec


@pytest.mark.integration
def test_tree_unit_suite():
    """The hierarchical-control-plane unit suite (core/cc/tree_unit):
    topology arithmetic, RankSet bitset union + wire round-trips,
    AggEntry merge/meta dedup, and the mini loopback trees — deep-tier
    sig mismatch propagating to every rank as an error entry, subtree
    sever leaving outside ranks negotiating. Tier-1: it runs in well
    under a second."""
    _build("tree_unit")
    r = subprocess.run([os.path.join(CCDIR, "tree_unit")],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.returncode, r.stdout,
                               r.stderr[-2000:])
    assert "TREE UNIT OK" in r.stdout, r.stdout


@pytest.mark.integration
def test_tree_mode_small_world():
    """stress_scale --tree at a small world (tier-1 smoke for the
    hierarchical path end-to-end: handshakes to per-aggregator
    listeners, merged kReadyAgg upward, relayed responses downward,
    identical agreed order — the binary exits non-zero on
    divergence)."""
    _build("stress_scale")
    rec = _run(16, rounds=10, extra=("--tree=4",))
    assert rec["mode"] == "tree" and rec["depth"] == 2, rec
    assert rec["connect_s"] < 30.0, rec
    assert rec["round_p95_ms"] < 2000.0, rec


@pytest.mark.integration
def test_flat_vs_tree_256_root_work():
    """The tree's load-bearing claim at 256 simulated ranks: the
    ROOT's per-round control-plane work (thread-CPU ns in
    parse/ingest/cut/fan-out — the term that must stay sub-cycle on a
    pod, where each node owns its core) drops by severalfold vs the
    flat star, and no aggregator inherits the root's burden. Gang
    wall-clock is deliberately NOT asserted tight here: on a 1-core
    CI host it measures the scheduler, not the protocol (see
    benchmarks/control_plane_scale.md round 9). Nightly: two 256-rank
    gangs are minutes of load on the CI box."""
    _build("stress_scale")
    flat = _run(256, rounds=15)
    tree = _run(256, rounds=15, extra=("--tree=32", "--linger=5000"))
    assert tree["mode"] == "tree" and tree["depth"] == 2, tree
    # Loose CI bounds (measured: flat ~0.9-1.3 ms/round, tree
    # ~0.22-0.35 ms/round, ratio ~3.7-5x on this host).
    assert tree["root_work_ms_per_round"] < \
        flat["root_work_ms_per_round"] / 1.5, (flat, tree)
    # Aggregators must not become the new hotspot: the busiest
    # non-root node stays well under the root it relieved.
    assert tree["max_nonroot_work_ms_per_round"] < \
        flat["root_work_ms_per_round"], (flat, tree)
    # The merge is real: the root ingests a small multiple of the
    # aggregator count, not one frame per worker.
    assert tree["root_frames_per_round"] < \
        flat["root_frames_per_round"] / 2, (flat, tree)


@pytest.mark.integration
def test_tree_wiring_4proc():
    """The Python wiring end-to-end through the real launcher:
    HOROVOD_CONTROL_TREE_ARITY=2 at 4 ranks places rank 2 UNDER the
    rank-1 aggregator; negotiated generic ops with per-rank metadata
    cross the two-hop aggregation path and come back correctly
    aggregated, tiers match native.tree_tier, and the
    hvd_control_tree_depth gauge / hvd_control_round_seconds
    histogram are live. Control-plane only — runs on jaxlibs without
    the cross-process data plane."""
    import sys
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "4",
         sys.executable, os.path.join("tests", "mp_worker_tree.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert r.stdout.count("TREE WIRE OK") == 4, r.stdout
    assert "tier=2" in r.stdout, r.stdout  # rank 2 really sat deeper


@pytest.mark.integration
def test_slow_worker_does_not_stall_healthy_ranks():
    """The broadcast pump's core claim, end-to-end: one raw-socket
    rank submits but NEVER reads its socket (a stalled TCP window —
    the flaky-host pod failure mode), with fat request metas
    inflating every agreed entry so its unread socket backs up within
    a few rounds. Healthy ranks must keep receiving every agreed
    batch. The pre-pump serial fan-out HANGS this binary (measured:
    the cycle thread blocks in send() to the stalled rank and the
    gang freezes); the pump completes it in well under a second."""
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, "stress_slow_worker"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    r = subprocess.run(
        [os.path.join(CCDIR, "stress_slow_worker"), "4", "60", "64"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["healthy_ok"] is True, rec
    # loose CI bound; measured 0.18s / worst-round 13ms on this host
    assert rec["elapsed_s"] < 60.0, rec
