"""Control-plane scale stress: the coordinator must absorb a pod-scale
connect storm and keep per-cycle agreement latency bounded well beyond
the 2-4 process integration tests (reference:
horovod/common/gloo/gloo_controller.cc leans on gloo's rendezvous and
tree broadcast for this property; this build's TCP coordinator has to
earn it explicitly — concurrent per-connection handshake threads, see
core/cc/controller.cc ServerAcceptLoop/HandshakeConn).

Runs the stress_scale binary (N in-process controllers over loopback)
at 32 and 64 workers and asserts:
  * every handshake of a CONCURRENT storm completes, fast;
  * agreement still reaches every rank in the same order (the binary
    exits non-zero on divergence);
  * steady-state agreement latency stays bounded.
Bounds are deliberately loose: CI hosts (this image exposes a single
CPU core to ~2N threads) measure scheduling noise, not the protocol.
The recorded curve for THIS host lives in benchmarks/
control_plane_scale.md.
"""

import json
import os
import shutil
import subprocess

import pytest

CCDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core", "cc")


def _run(workers: int, rounds: int = 15, tensors: int = 8) -> dict:
    r = subprocess.run(
        [os.path.join(CCDIR, "stress_scale"), str(workers),
         str(rounds), str(tensors)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.integration
def test_control_plane_scales_to_64_workers():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, "stress_scale"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]

    for workers in (32, 64):
        rec = _run(workers)
        # Concurrent connect storm: N-1 simultaneous mutual
        # challenge-response handshakes, all through one coordinator.
        assert rec["connect_s"] < 30.0, rec
        # Steady-state agreement: every rank sees every batch within
        # a loose bound (single-core CI scheduling noise included).
        assert rec["round_p95_ms"] < 2000.0, rec


@pytest.mark.integration
def test_slow_worker_does_not_stall_healthy_ranks():
    """The broadcast pump's core claim, end-to-end: one raw-socket
    rank submits but NEVER reads its socket (a stalled TCP window —
    the flaky-host pod failure mode), with fat request metas
    inflating every agreed entry so its unread socket backs up within
    a few rounds. Healthy ranks must keep receiving every agreed
    batch. The pre-pump serial fan-out HANGS this binary (measured:
    the cycle thread blocks in send() to the stalled rank and the
    gang freezes); the pump completes it in well under a second."""
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, "stress_slow_worker"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    r = subprocess.run(
        [os.path.join(CCDIR, "stress_slow_worker"), "4", "60", "64"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["healthy_ok"] is True, rec
    # loose CI bound; measured 0.18s / worst-round 13ms on this host
    assert rec["elapsed_s"] < 60.0, rec
