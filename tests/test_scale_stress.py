"""Control-plane scale stress: the coordinator must absorb a pod-scale
connect storm and keep per-cycle agreement latency bounded well beyond
the 2-4 process integration tests (reference:
horovod/common/gloo/gloo_controller.cc leans on gloo's rendezvous and
tree broadcast for this property; this build's TCP coordinator has to
earn it explicitly — concurrent per-connection handshake threads, see
core/cc/controller.cc ServerAcceptLoop/HandshakeConn).

Runs the stress_scale binary (N in-process controllers over loopback)
at 32 and 64 workers and asserts:
  * every handshake of a CONCURRENT storm completes, fast;
  * agreement still reaches every rank in the same order (the binary
    exits non-zero on divergence);
  * steady-state agreement latency stays bounded.
Bounds are deliberately loose: CI hosts (this image exposes a single
CPU core to ~2N threads) measure scheduling noise, not the protocol.
The recorded curve for THIS host lives in benchmarks/
control_plane_scale.md.
"""

import json
import os
import shutil
import subprocess

import pytest

CCDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core", "cc")


def _run(workers: int, rounds: int = 15, tensors: int = 8) -> dict:
    r = subprocess.run(
        [os.path.join(CCDIR, "stress_scale"), str(workers),
         str(rounds), str(tensors)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-2000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.integration
def test_control_plane_scales_to_64_workers():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(["make", "-C", CCDIR, "stress_scale"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]

    for workers in (32, 64):
        rec = _run(workers)
        # Concurrent connect storm: N-1 simultaneous mutual
        # challenge-response handshakes, all through one coordinator.
        assert rec["connect_s"] < 30.0, rec
        # Steady-state agreement: every rank sees every batch within
        # a loose bound (single-core CI scheduling noise included).
        assert rec["round_p95_ms"] < 2000.0, rec
