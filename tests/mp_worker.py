"""Worker script for launcher integration tests: exercises the eager
collective API across REAL processes (the reference's
`horovodrun -np 2 pytest` analog, SURVEY.md §4 tier 1)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# 64-bit rows of the dtype matrix need real x64 (this process is NOT
# under conftest.py's jax_enable_x64).
os.environ.setdefault("JAX_ENABLE_X64", "1")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Pin the launch-overhead term to zero so the skewed-alltoall phase
# asserts the BYTE side of the auto heuristic deterministically (the
# launch-aware side is unit-tested in test_dispatch_kernels).
os.environ.setdefault("HOROVOD_LAUNCH_OVERHEAD_US", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    nsz = int(os.environ.get("HOROVOD_SIZE", "1"))
    half = hvd.ProcessSet(list(range(max(nsz // 2, 1))))
    hvd.init(process_sets=[half])
    r, n = hvd.rank(), hvd.size()
    assert n == int(os.environ["HOROVOD_SIZE"]), (n, os.environ)
    print(f"worker rank={r} size={n} devices={jax.device_count()}")

    # allreduce (average)
    out = hvd.allreduce(jnp.array([float(r + 1), 2.0]), name="t0")
    expect = np.array([(sum(range(1, n + 1))) / n, 2.0])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)

    # sum + prescale
    out = hvd.allreduce(jnp.array([1.0]), op=hvd.Sum,
                        prescale_factor=2.0, name="t1")
    np.testing.assert_allclose(np.asarray(out), [2.0 * n])

    # grouped allreduce, mixed dtypes
    outs = hvd.grouped_allreduce(
        [jnp.ones((3,), jnp.float32) * r, jnp.ones((2,), jnp.float64)],
        op=hvd.Sum, name="t2")
    np.testing.assert_allclose(np.asarray(outs[0]),
                               np.full(3, sum(range(n))))
    np.testing.assert_allclose(np.asarray(outs[1]), np.full(2, n))

    # broadcast
    out = hvd.broadcast(jnp.arange(4.0) * (r + 1), root_rank=1 % n,
                        name="t3")
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(4.0) * ((1 % n) + 1))

    # uneven allgather
    out = hvd.allgather(jnp.full((r + 1, 2), float(r)), name="t4")
    expect = np.concatenate(
        [np.full((i + 1, 2), float(i)) for i in range(n)])
    np.testing.assert_allclose(np.asarray(out), expect)

    # alltoall with splits
    x = jnp.arange(float(n * 2)).reshape(n * 2)[:, None]
    out, recv = hvd.alltoall(x, splits=[2] * n, name="t5")
    assert out.shape[0] == 2 * n

    # UNEVEN alltoall: rank r sends (d+1)*(r+1) rows to dest d — both
    # the send and the receive split vectors differ per rank, all
    # carried through the negotiation metadata (reference:
    # MPI_Alltoallv semantics via HorovodAlltoallOp splits)
    sends = [(d + 1) * (r + 1) for d in range(n)]
    rows = sum(sends)
    x = jnp.full((rows, 2), float(r))
    out, recv = hvd.alltoall(x, splits=sends, name="t5u")
    want_recv = [(r + 1) * (src + 1) for src in range(n)]
    np.testing.assert_array_equal(np.asarray(recv), want_recv)
    assert out.shape == (sum(want_recv), 2)
    # block from src has value src
    off = 0
    for src in range(n):
        np.testing.assert_allclose(
            np.asarray(out[off:off + want_recv[src]]), float(src))
        off += want_recv[src]

    # SKEWED alltoall (the MoE hot path: most rows stay local). The
    # ragged exchange must move ~sum(cross splits) rows on the wire,
    # not n * maxsplit (reference: MPI_Alltoallv exact counts).
    sends = [64 if d == r else 1 for d in range(n)]
    x = jnp.concatenate(
        [jnp.full((sends[d], 2), float(10 * r + d)) for d in range(n)])
    out, recv = hvd.alltoall(x, splits=sends, name="t5s")
    want_recv = [64 if src == r else 1 for src in range(n)]
    np.testing.assert_array_equal(np.asarray(recv), want_recv)
    off = 0
    for src in range(n):
        np.testing.assert_allclose(
            np.asarray(out[off:off + want_recv[src]]),
            float(10 * src + r))
        off += want_recv[src]
    from horovod_tpu.ops import dispatch as _dispatch
    st = _dispatch.last_alltoall_stats()
    assert st["path"] == "ragged", st
    assert st["wire_rows"] == n - 1, st        # 1-row bucket per round
    assert st["padded_rows"] == n * 64, st     # what padding would move

    # reducescatter
    x = jnp.ones((2 * n, 3)) * (r + 1)
    out = hvd.reducescatter(x, op=hvd.Sum, name="t6")
    np.testing.assert_allclose(
        np.asarray(out), np.full((2, 3), sum(range(1, n + 1))))

    # hvd.flax.DistributedTrainState: rank-DIFFERENT init must equal
    # rank 0's after create (broadcast), and a step on rank-different
    # grads must keep params identical (averaged reduction).
    import optax
    st_flax = hvd.flax.DistributedTrainState.create(
        apply_fn=lambda v, x: x,
        params={"w": jnp.full((3,), float(r + 1))}, tx=optax.sgd(1.0))
    np.testing.assert_allclose(np.asarray(st_flax.params["w"]), 1.0)
    st_flax = st_flax.apply_gradients(
        grads={"w": jnp.full((3,), float(r))})
    want_w = 1.0 - sum(range(n)) / n
    np.testing.assert_allclose(np.asarray(st_flax.params["w"]),
                               want_w, rtol=1e-6)
    stats = hvd.flax.sync_batch_stats(
        {"m": jnp.full((2,), float(r))})
    np.testing.assert_allclose(np.asarray(stats["m"]),
                               sum(range(n)) / n)

    # grouped allgather (uneven dims per tensor) + grouped
    # reducescatter under ONE umbrella handle each (reference:
    # grouped_allgather / grouped_reducescatter in torch/mpi_ops.py)
    outs = hvd.grouped_allgather(
        [jnp.full((r + 1, 2), float(r)), jnp.full((1,), float(r))],
        name="t6g")
    np.testing.assert_allclose(
        np.asarray(outs[0]),
        np.concatenate([np.full((i + 1, 2), float(i))
                        for i in range(n)]))
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.arange(float(n)))
    outs = hvd.grouped_reducescatter(
        [jnp.ones((2 * n, 3)) * (r + 1), jnp.ones((n,)) * (r + 1)],
        op=hvd.Sum, name="t6gr")
    np.testing.assert_allclose(
        np.asarray(outs[0]), np.full((2, 3), sum(range(1, n + 1))))
    np.testing.assert_allclose(
        np.asarray(outs[1]), np.full((1,), sum(range(1, n + 1))))

    # sparse allreduce (BCOO): rank-dependent nnz, rank 0 contributes
    # ZERO rows (the empty-contribution edge of the uneven allgather),
    # every other rank touches row 1 (cross-rank duplicate coalescing)
    # (reference: torch mpi_ops sparse allreduce via allgather).
    from jax.experimental import sparse as jsparse
    if r == 0:
        sp = jsparse.BCOO(
            (jnp.zeros((0, 2)), jnp.zeros((0, 1), jnp.int32)),
            shape=(5, 2))
    else:
        sp = jsparse.BCOO(
            (jnp.full((2, 2), float(r)),
             jnp.array([[1], [min(r + 1, 4)]], jnp.int32)),
            shape=(5, 2))
    out = hvd.sparse_allreduce(sp, op=hvd.Sum, name="t7.sparse")
    want = np.zeros((5, 2))
    for rr in range(1, n):
        want[1] += rr
        want[min(rr + 1, 4)] += rr
    np.testing.assert_allclose(np.asarray(out.todense()), want)

    # dtype x op matrix on the negotiated path (reference analog:
    # test_torch.py's exhaustive dtype/op coverage under -np 2).
    # Rank r contributes full((r+2)); closed forms below.
    matrix_dtypes = [jnp.float32, jnp.float64, jnp.bfloat16,
                     jnp.float16, jnp.int32, jnp.int64, jnp.uint8]
    vals = [i + 2 for i in range(n)]
    for dt in matrix_dtypes:
        is_float = jnp.issubdtype(dt, jnp.floating)
        ops = [(hvd.Sum, float(sum(vals))),
               (hvd.Min, float(min(vals))),
               (hvd.Max, float(max(vals))),
               (hvd.Product, float(np.prod(vals)))]
        if is_float:
            ops.append((hvd.Average, sum(vals) / n))
        for op_, want in ops:
            x = jnp.full((4, 3), r + 2, dt)
            out = hvd.allreduce(x, op=op_,
                                name=f"mx.{np.dtype(dt).name}.{op_}")
            assert out.dtype == x.dtype, (out.dtype, dt)
            tol = 5e-2 if dt in (jnp.bfloat16, jnp.float16) else 1e-6
            np.testing.assert_allclose(
                np.asarray(out, np.float64), np.full((4, 3), want),
                rtol=tol)
    # bool allgather/broadcast (the reference covers bool paths too)
    out = hvd.allgather(jnp.asarray([r % 2 == 0] * 2), name="mx.bool")
    assert out.dtype == jnp.bool_ and out.shape[0] == 2 * n
    out = hvd.broadcast(jnp.asarray([True, False]), root_rank=0,
                        name="mx.bool.bc")
    assert bool(out[0]) and not bool(out[1])

    # SUBSET process-set eager ops dispatch inline (the negotiation is
    # world-scoped; waiting on non-members would hang) — must complete
    # with member-only semantics while the world controller is live.
    if r in half.ranks:
        out = hvd.allreduce(jnp.full((3,), float(r + 1)), op=hvd.Sum,
                            name="subset_ar", process_set=half)
        np.testing.assert_allclose(
            np.asarray(out),
            np.full(3, float(sum(i + 1 for i in half.ranks))))

    # barrier + broadcast_parameters + optimizer functions
    hvd.barrier()
    params = {"w": jnp.ones((2, 2)) * r}
    params = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.0)

    # broadcast_object
    obj = hvd.broadcast_object({"epoch": r * 10}, root_rank=0)
    assert obj == {"epoch": 0}

    # allgather_object: rank-varying payload SIZES (uneven gather)
    got = hvd.allgather_object({"rank": r, "pad": "x" * (10 * (r + 1))})
    assert [g["rank"] for g in got] == list(range(hvd.size())), got
    assert all(len(g["pad"]) == 10 * (i + 1)
               for i, g in enumerate(got)), got

    print(f"worker rank={r}: ALL OK")
    hvd.shutdown()


if __name__ == "__main__":
    main()
