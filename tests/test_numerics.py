"""Numerical-integrity subsystem tests (numerics.py): finite-flag
computation and its ride through the reduction paths, the coordinated
skip-step wrapper (incl. the disabled-is-identity contract, the HLO
no-op acceptance check, and escalation), the distributed loss scaler's
backoff/growth schedule, digest determinism for the replica-divergence
sentinel, the numerics.grad/numerics.param chaos seams, and — behind
the multiproc capability probe — the fixed-seed 2-rank chaos runs:
rank-local NaN => one coordinated skip everywhere with bitwise-equal
replicas, and a single bit-flip => ReplicaDivergenceError naming the
corrupted rank, recovered through elastic restore."""

import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, numerics
from horovod_tpu.common.exceptions import (HorovodInternalError,
                                           ReplicaDivergenceError)
from horovod_tpu.metrics import REGISTRY

from tests.test_elastic import (REPO, launch, make_env, read_logs,
                                write_discovery)

_NO_MULTIPROC = ("this jaxlib's CPU backend cannot run cross-process "
                 "collectives (affects every multiprocess "
                 "integration test)")


@pytest.fixture(autouse=True)
def disarm_faults():
    faults.configure(None)
    yield
    faults.configure(None)


@pytest.fixture(scope="module")
def multiproc_backend():
    """Cheap capability probe (same gate as test_chaos.py)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c",
         "import jax.numpy as jnp; import horovod_tpu as hvd; "
         "hvd.init(); hvd.allreduce(jnp.ones(4), name='probe'); "
         "hvd.shutdown()"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip(_NO_MULTIPROC)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


def _skip_if_no_multiproc(out, returncode):
    if returncode != 0 and \
            "Multiprocess computations aren't implemented" in out:
        pytest.skip(_NO_MULTIPROC)


# ---------------------------------------------------------------------------
# finite flags
# ---------------------------------------------------------------------------

class TestFiniteFlags:
    def test_all_finite_basic(self):
        assert bool(numerics.all_finite({"a": jnp.ones(3)}))
        assert not bool(numerics.all_finite(
            {"a": jnp.array([1.0, jnp.nan])}))
        assert not bool(numerics.all_finite(
            {"a": jnp.ones(2), "b": jnp.array([jnp.inf])}))

    def test_integer_leaves_ignored_and_empty_tree_finite(self):
        assert bool(numerics.all_finite({"i": jnp.array([1, 2])}))
        assert bool(numerics.all_finite({}))

    def test_local_finite_flag_wire_form(self):
        f = numerics.local_finite_flag([jnp.ones(2)])
        assert f.dtype == jnp.float32 and float(f) == 1.0
        f = numerics.local_finite_flag([jnp.array([jnp.nan])])
        assert float(f) == 0.0

    def test_imprint_poisons_only_on_veto(self):
        t = {"a": jnp.ones(3), "i": jnp.array([1, 2])}
        ok = numerics.imprint_non_finite(t, True)
        np.testing.assert_array_equal(np.asarray(ok["a"]), 1.0)
        bad = numerics.imprint_non_finite(t, False)
        assert np.isnan(np.asarray(bad["a"])).all()
        # integer leaves are left alone (finite by construction)
        np.testing.assert_array_equal(np.asarray(bad["i"]), [1, 2])


# ---------------------------------------------------------------------------
# guard_non_finite
# ---------------------------------------------------------------------------

class TestGuard:
    def test_disabled_returns_inner_unchanged(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        inner = optax.sgd(0.1)
        assert numerics.guard_non_finite(inner) is inner

    def test_finite_step_matches_inner(self):
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        params = {"w": jnp.arange(4.0)}
        st = g.init(params)
        up, st = g.update({"w": jnp.ones(4)}, st, params)
        np.testing.assert_allclose(np.asarray(up["w"]), -0.1)
        assert numerics.consecutive_skips(st) == 0

    def test_skip_zeroes_update_and_freezes_inner_state(self):
        g = numerics.guard_non_finite(optax.adam(0.1), enabled=True)
        params = {"w": jnp.ones(4)}
        st = g.init(params)
        up, st1 = g.update({"w": jnp.ones(4)}, st, params)
        inner_before = jax.tree_util.tree_map(np.asarray,
                                              st1.inner_state)
        up, st2 = g.update({"w": jnp.array([1.0, jnp.nan, 1, 1])},
                           st1, params)
        assert np.all(np.asarray(up["w"]) == 0)
        assert numerics.consecutive_skips(st2) == 1
        assert int(st2.total_skips) == 1
        # Adam's moments/count did NOT advance on the skipped step
        for a, b in zip(jax.tree_util.tree_leaves(inner_before),
                        jax.tree_util.tree_leaves(
                            jax.tree_util.tree_map(
                                np.asarray, st2.inner_state))):
            np.testing.assert_array_equal(a, b)
        # a clean step resets the consecutive counter
        up, st3 = g.update({"w": jnp.ones(4)}, st2, params)
        assert numerics.consecutive_skips(st3) == 0
        assert int(st3.total_skips) == 1

    def test_skip_counted_in_metrics(self):
        before = sum((REGISTRY.snapshot().get(
            "hvd_skipped_steps_total") or {}).values())
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        params = {"w": jnp.ones(2)}
        st = g.init(params)
        g.update({"w": jnp.array([jnp.nan, 1.0])}, st, params)
        after = REGISTRY.snapshot()["hvd_skipped_steps_total"]
        assert sum(after.values()) == before + 1
        assert after[("non_finite",)] >= 1

    def test_escalation_raises_horovod_internal_error(self):
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True,
                                      max_consecutive=2)
        params = {"w": jnp.ones(2)}
        st = g.init(params)
        bad = {"w": jnp.array([jnp.nan, 1.0])}
        _, st = g.update(bad, st, params)
        with pytest.raises(HorovodInternalError, match="consecutive"):
            g.update(bad, st, params)

    def test_jit_path_counts_in_state_and_check_escalation(self):
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        params = {"w": jnp.ones(2)}
        st = g.init(params)
        upd = jax.jit(lambda u, s, p: g.update(u, s, p))
        bad = {"w": jnp.array([jnp.nan, 1.0])}
        _, st = upd(bad, st, params)
        _, st = upd(bad, st, params)
        assert numerics.consecutive_skips(st) == 2
        numerics.check_escalation(st, max_consecutive=3)  # below: ok
        with pytest.raises(HorovodInternalError):
            numerics.check_escalation(st, max_consecutive=2)

    def test_dgt_eager_ride_skips_and_recovers(self, hvd_single,
                                               monkeypatch):
        """The eager fused flag ride end to end at world size 1: NaN
        grads => zeroed update + counted skip; clean grads => exact
        SGD update (the flag leaf must not leak into the output)."""
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        opt = hvd.DistributedOptimizer(
            numerics.guard_non_finite(optax.sgd(0.1), enabled=True))
        params = {"w": jnp.arange(4.0), "b": jnp.ones(2)}
        st = opt.init(params)
        up, st = opt.update(
            {"w": jnp.ones(4), "b": jnp.ones(2)}, st, params)
        np.testing.assert_allclose(np.asarray(up["w"]), -0.1)
        up, st = opt.update(
            {"w": jnp.array([1.0, jnp.nan, 1, 1]), "b": jnp.ones(2)},
            st, params)
        assert np.all(np.asarray(up["w"]) == 0)
        assert np.all(np.asarray(up["b"]) == 0)
        assert numerics.consecutive_skips(st) == 1

    def test_dgt_compressed_reduction_still_vetoes(self, hvd_single,
                                                   monkeypatch):
        """With lossy fp16/bf16 compression the vote must NOT ride the
        compressed group (a summed count stops being integer-exact at
        scale); the exact Min allreduce carries it instead — the skip
        still happens."""
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        opt = hvd.DistributedOptimizer(
            numerics.guard_non_finite(optax.sgd(0.1), enabled=True),
            compression=hvd.Compression.fp16)
        params = {"w": jnp.arange(4.0, dtype=jnp.float32)}
        st = opt.init(params)
        up, st = opt.update(
            {"w": jnp.array([1.0, jnp.nan, 1, 1], jnp.float32)},
            st, params)
        assert np.all(np.asarray(up["w"]) == 0)
        assert numerics.consecutive_skips(st) == 1
        up, st = opt.update({"w": jnp.ones(4, jnp.float32)}, st,
                            params)
        assert np.all(np.asarray(up["w"]) != 0)
        assert numerics.consecutive_skips(st) == 0

    def test_grad_seam_fires_without_guard(self, hvd_single,
                                           monkeypatch):
        """Negative control: an armed numerics.grad spec injects (and
        counts the fire) even with the guard OFF — the poison then
        propagates, demonstrating what the guard prevents. An armed
        spec must never be an unlogged no-op."""
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        faults.configure("numerics.grad:nan:at=1", seed=1)
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.ones(4)}
        st = opt.init(params)
        up, st = opt.update({"w": jnp.ones(4)}, st, params)
        assert not bool(numerics.all_finite(up))   # poison propagated
        fired = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
        assert fired.get(("numerics.grad", "nan"), 0) >= 1

    def test_dgt_sum_op_ride(self, hvd_single, monkeypatch):
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        opt = hvd.DistributedOptimizer(
            numerics.guard_non_finite(optax.sgd(1.0), enabled=True),
            op=hvd.Sum)
        params = {"w": jnp.zeros(3)}
        st = opt.init(params)
        up, st = opt.update({"w": jnp.ones(3)}, st, params)
        np.testing.assert_allclose(np.asarray(up["w"]), -1.0)
        up, st = opt.update({"w": jnp.full(3, jnp.inf)}, st, params)
        assert np.all(np.asarray(up["w"]) == 0)


class TestTrainStepGuard:
    def _loss(self, params, batch):
        return jnp.mean((batch * params["w"]) ** 2)

    def _mesh(self):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:8]), axis_names=("proc",))

    def test_guarded_step_skips_nan_batch(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        from horovod_tpu.parallel.train import build_train_step
        g = numerics.guard_non_finite(optax.sgd(0.1), enabled=True)
        step = build_train_step(self._loss, g, self._mesh(),
                                donate=False)
        params = {"w": jnp.ones(())}
        st = g.init(params)
        p2, o2, _ = step(params, st, jnp.arange(8.0))
        assert float(p2["w"]) != 1.0
        assert numerics.consecutive_skips(o2) == 0
        bad = jnp.arange(8.0).at[3].set(jnp.nan)
        p3, o3, _ = step(params, st, bad)
        assert float(p3["w"]) == 1.0          # coordinated skip
        assert numerics.consecutive_skips(o3) == 1

    def test_disabled_guard_lowers_to_identical_hlo(self, monkeypatch):
        """Acceptance: with no numerics knobs set, wrapping the
        optimizer in guard_non_finite changes NOTHING in the lowered
        program — byte-identical HLO text."""
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        from horovod_tpu.parallel.train import build_train_step
        mesh = self._mesh()
        inner = optax.sgd(0.1)
        s1 = build_train_step(self._loss,
                              numerics.guard_non_finite(inner),
                              mesh, donate=False)
        s2 = build_train_step(self._loss, inner, mesh, donate=False)
        params = {"w": jnp.ones(())}
        st = inner.init(params)
        batch = jnp.arange(8.0)
        assert s1.lower(params, st, batch).as_text() == \
            s2.lower(params, st, batch).as_text()


# ---------------------------------------------------------------------------
# DistributedLossScaler
# ---------------------------------------------------------------------------

class TestLossScaler:
    def test_defaults_from_knobs(self):
        sc = hvd.DistributedLossScaler()
        assert sc.init_scale == 65536.0
        assert sc.growth_interval == 2000

    def test_backoff_on_overflow(self):
        sc = hvd.DistributedLossScaler(init_scale=16.0,
                                       growth_interval=4)
        st = sc.init()
        st = sc.update(st, False)
        assert float(st.scale) == 8.0 and int(st.growth_count) == 0
        st = sc.update(st, False)
        assert float(st.scale) == 4.0

    def test_growth_after_interval_clean_steps(self):
        sc = hvd.DistributedLossScaler(init_scale=8.0,
                                       growth_interval=3)
        st = sc.init()
        for _ in range(2):
            st = sc.update(st, True)
            assert float(st.scale) == 8.0
        st = sc.update(st, True)   # 3rd clean step: grow + reset
        assert float(st.scale) == 16.0
        assert int(st.growth_count) == 0

    def test_backoff_resets_growth_count_and_floors(self):
        sc = hvd.DistributedLossScaler(init_scale=2.0,
                                       growth_interval=10,
                                       min_scale=1.0)
        st = sc.init()
        st = sc.update(st, True)
        assert int(st.growth_count) == 1
        st = sc.update(st, False)
        assert int(st.growth_count) == 0
        st = sc.update(st, False)
        assert float(st.scale) == 1.0   # floored, never 0

    def test_scale_unscale_roundtrip_and_jit(self):
        sc = hvd.DistributedLossScaler(init_scale=1024.0)
        st = sc.init()
        loss = jnp.float32(3.0)
        assert float(sc.scale(loss, st)) == 3072.0
        grads = {"w": jnp.full(3, 2048.0)}
        out = sc.unscale(grads, st)
        np.testing.assert_allclose(np.asarray(out["w"]), 2.0)
        st2 = jax.jit(sc.update)(st, jnp.asarray(False))
        assert float(st2.scale) == 512.0

    def test_invalid_factors_rejected(self):
        with pytest.raises(ValueError):
            hvd.DistributedLossScaler(growth_factor=1.0)
        with pytest.raises(ValueError):
            hvd.DistributedLossScaler(backoff_factor=1.5)


# ---------------------------------------------------------------------------
# digests / divergence sentinel
# ---------------------------------------------------------------------------

class TestDigest:
    def test_deterministic_across_recomputation(self):
        t = {"w": jnp.arange(16.0), "b": jnp.ones((2, 3))}
        assert numerics.params_digest(t) == numerics.params_digest(
            {"w": jnp.arange(16.0), "b": jnp.ones((2, 3))})

    def test_sensitive_to_value_dtype_shape_and_path(self):
        w = jnp.arange(4.0, dtype=jnp.float32)
        base = numerics.params_digest({"w": w})
        assert base != numerics.params_digest(
            {"w": w.at[2].add(1e-6)})
        assert base != numerics.params_digest(
            {"w": w.astype(jnp.float64)})
        assert base != numerics.params_digest(
            {"w": w.reshape(2, 2)})
        assert base != numerics.params_digest({"v": w})

    def test_check_noop_at_world_size_one(self, hvd_single):
        numerics.check_replica_divergence({"w": jnp.ones(4)})

    def test_replica_divergence_error_is_restorable(self):
        err = ReplicaDivergenceError("boom", divergent_ranks=(1,))
        assert isinstance(err, HorovodInternalError)
        assert err.divergent_ranks == (1,)

    def _check_with_world(self, monkeypatch, digests):
        """Run check_replica_divergence against a faked allgather
        (the wire is 8 bytes/rank; the consensus logic is pure)."""
        from horovod_tpu.common import basics
        from horovod_tpu.optim import functions
        monkeypatch.setattr(basics, "is_initialized", lambda: True)
        monkeypatch.setattr(basics, "size", lambda: len(digests))
        monkeypatch.setattr(
            functions, "allgather_object",
            lambda obj, name=None, process_set=None: list(digests))
        numerics.check_replica_divergence({"w": jnp.ones(2)})

    def test_agreeing_replicas_pass(self, monkeypatch):
        self._check_with_world(monkeypatch, [7, 7, 7])

    def test_divergent_minority_named(self, monkeypatch):
        with pytest.raises(ReplicaDivergenceError) as ei:
            self._check_with_world(monkeypatch, [7, 7, 9, 7])
        assert ei.value.divergent_ranks == (2,)
        assert "divergent ranks [2]" in str(ei.value)

    def test_two_rank_tie_blames_higher_rank(self, monkeypatch):
        """1-vs-1 split: consensus ties break toward the group holding
        rank 0 (whose state elastic sync re-broadcasts), so the
        corrupted higher rank is the one named."""
        with pytest.raises(ReplicaDivergenceError) as ei:
            self._check_with_world(monkeypatch, [7, 9])
        assert ei.value.divergent_ranks == (1,)
        # a 1-vs-1 split cannot PROVE which side is corrupted; the
        # error must say so instead of claiming a clean recovery
        assert "AMBIGUOUS" in str(ei.value)

    def test_strict_majority_is_not_flagged_ambiguous(self,
                                                      monkeypatch):
        with pytest.raises(ReplicaDivergenceError) as ei:
            self._check_with_world(monkeypatch, [7, 7, 9])
        assert "AMBIGUOUS" not in str(ei.value)

    def test_rank0_divergent_fails_hard_not_restorable(self,
                                                       monkeypatch):
        """When rank 0 — the elastic sync broadcast root — holds the
        minority digest, restore + sync would re-broadcast the
        CORRUPTED state onto healthy ranks (laundering the SDC). That
        case must NOT be a HorovodInternalError the elastic loop
        swallows: it fails hard."""
        with pytest.raises(RuntimeError, match="broadcast root") as ei:
            self._check_with_world(monkeypatch, [9, 7, 7, 7])
        assert not isinstance(ei.value, HorovodInternalError)


# ---------------------------------------------------------------------------
# chaos seams
# ---------------------------------------------------------------------------

class TestSeams:
    def test_grammar_accepts_new_points(self):
        rules = faults.parse(
            "numerics.grad:nan:at=3,rank=1;numerics.param:flip:at=5")
        assert [(r.point, r.action) for r in rules] == [
            ("numerics.grad", "nan"), ("numerics.param", "flip")]

    @pytest.mark.parametrize("bad", [
        "numerics.grad:flip",      # flip is a param-seam action
        "numerics.param:nan",      # nan is a grad-seam action
        "wire.send:nan",           # numerics actions stay at numerics
    ])
    def test_grammar_rejects_cross_seam_actions(self, bad):
        with pytest.raises(ValueError):
            faults.parse(bad)

    def test_corrupt_grads_nan_and_inf(self):
        for act, pred in (("nan", np.isnan), ("inf", np.isinf)):
            faults.configure(f"numerics.grad:{act}", seed=1)
            leaves = [jnp.array([5, 6]), jnp.ones(4)]
            out = numerics.maybe_corrupt_grads(leaves)
            # first INEXACT leaf poisoned in exactly one element
            assert pred(np.asarray(out[1])).sum() == 1
            np.testing.assert_array_equal(np.asarray(out[0]), [5, 6])

    def test_corrupt_grads_disarmed_is_identity(self):
        leaves = [jnp.ones(4)]
        assert numerics.maybe_corrupt_grads(leaves) is leaves

    def test_corrupt_grads_skips_sparse_leaves(self):
        """A BCOO leaf in the gradient list must be passed over, not
        crash the seam — and ANY armed plan reaches this code when
        the guard is on (faults.active() is plan-global), so a
        non-numerics spec must be harmless too."""
        from jax.experimental import sparse as jsparse
        bcoo = jsparse.BCOO.fromdense(jnp.zeros((4, 2)).at[1].set(1.0))
        # armed, but with a rule at a DIFFERENT point
        faults.configure("wire.send:drop:p=0.0", seed=1)
        out = numerics.maybe_corrupt_grads([bcoo, jnp.ones(3)])
        assert out[0] is bcoo
        np.testing.assert_array_equal(np.asarray(out[1]), 1.0)
        # a firing nan rule poisons the first DENSE leaf only
        faults.configure("numerics.grad:nan", seed=1)
        out = numerics.maybe_corrupt_grads([bcoo, jnp.ones(3)])
        assert out[0] is bcoo
        assert np.isnan(np.asarray(out[1])).sum() == 1

    def test_flip_param_changes_one_bit(self):
        faults.configure("numerics.param:flip:times=1", seed=1)
        t = {"w": jnp.arange(8.0)}
        before = numerics.params_digest(t)
        out = numerics.maybe_flip_param(t)
        assert numerics.params_digest(out) != before
        a, b = np.asarray(t["w"]), np.asarray(out["w"])
        assert (a.view(np.int32) != b.view(np.int32)).sum() == 1
        # times=1 exhausted: second call is a no-op
        assert numerics.maybe_flip_param(out) is out

    def test_on_commit_runs_flip_and_counts_commits(self, monkeypatch):
        faults.configure("numerics.param:flip:at=1", seed=1)
        monkeypatch.setenv("HOROVOD_NUMERICS_CHECK_EVERY", "2")

        class FakeState:
            params = {"w": jnp.arange(4.0)}

        st = FakeState()
        before = numerics.params_digest(st.params)
        numerics.on_commit(st)
        assert numerics.params_digest(st.params) != before
        assert st._numerics_commit_count == 1
        numerics.on_commit(st)   # 2nd commit: divergence check runs
        assert st._numerics_commit_count == 2  # (no-op pre-init)

    def test_on_commit_registers_cadence_counter_as_elastic_state(self):
        """The digest allgather is collective, so the cadence counter
        must ride commit/restore/sync like any elastic attr — on a
        real ObjectState it self-registers into _known_attrs (synced
        to joiners, rolled back in lockstep on restore)."""
        hvd.init(config_overrides={"HOROVOD_NUMERICS_CHECK_EVERY": 5})
        try:
            from horovod_tpu.elastic.state import JaxState
            st = JaxState(params={"w": jnp.ones(2)}, step=0)
            st.commit()
            assert "_numerics_commit_count" in st._known_attrs
            assert st._numerics_commit_count == 1
            st.commit()
            st.sync()   # size 1 broadcast; the counter round-trips
            assert st._numerics_commit_count == 2
            st._numerics_commit_count = 99
            st.restore()   # rolls back with the rest of the state
            assert st._numerics_commit_count == 2
        finally:
            hvd.shutdown()

    def test_on_commit_disarmed_fast_path_overhead(self, monkeypatch):
        """Tier-1 perf guard mirroring faults.fire's: with no knobs
        and faults disarmed, the per-commit numerics hook is a few
        lookups. Generous bound for a loaded CI host."""
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD", raising=False)
        monkeypatch.delenv("HOROVOD_NUMERICS_CHECK_EVERY",
                           raising=False)

        class FakeState:
            params = None

        st = FakeState()
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            numerics.on_commit(st)
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 50e-6, f"{per_call * 1e6:.2f} us/call"


# ---------------------------------------------------------------------------
# lazy-flax satellite (rides this PR)
# ---------------------------------------------------------------------------

def test_flax_loads_lazily_not_at_import_time():
    """`import horovod_tpu` must not drag the external flax package
    in (it is an opt-in frontend like horovod_tpu.torch); hvd.flax
    still resolves on first touch."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; import horovod_tpu as hvd; "
         "assert 'flax' not in sys.modules, 'flax imported eagerly'; "
         "assert 'horovod_tpu.flax' not in sys.modules; "
         "_ = hvd.flax.DistributedTrainState; "
         "import horovod_tpu.flax as hf; "
         "assert hf is hvd.flax"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


# ---------------------------------------------------------------------------
# 2-rank chaos (tier-1, fixed seed, behind the capability probe)
# ---------------------------------------------------------------------------

@pytest.mark.integration
class TestNumericsChaos:
    def test_rank_local_nan_one_coordinated_skip(self, tmp_path,
                                                 multiproc_backend):
        """numerics.grad:nan:at=3,rank=1 — one rank's gradient goes
        NaN once, pre-reduction. Every rank must skip exactly that one
        step (each asserts hvd_skipped_steps_total == 1 locally) and
        finish with bitwise-identical parameters (digest allgather
        asserted inside the worker)."""
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["HOROVOD_NUMERICS_GUARD"] = "1"
        env["HOROVOD_FAULTS"] = "numerics.grad:nan:at=3,rank=1"
        env["HOROVOD_FAULTS_SEED"] = "7"
        env["NUMERICS_TEST_STEPS"] = "6"
        env["NUMERICS_TEST_EXPECT_SKIPS"] = "1"
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, os.path.join("tests",
                                          "mp_worker_numerics.py")],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300)
        out = r.stdout + r.stderr
        _skip_if_no_multiproc(out, r.returncode)
        assert r.returncode == 0, out
        assert "faults: firing nan at numerics.grad" in out, out
        assert "numerics ok rank 0 skips 1" in out, out
        assert "numerics ok rank 1 skips 1" in out, out

    def test_param_bitflip_divergence_detected_and_restored(
            self, tmp_path, multiproc_backend):
        """numerics.param:flip:at=4,rank=1 under the elastic worker
        with the sentinel armed (CHECK_EVERY=2): the flip at commit 4
        is caught by that commit's digest check, the raised
        ReplicaDivergenceError names rank 1, and the elastic retry
        loop restores + rank-0-syncs — the job completes with both
        ranks done."""
        script = write_discovery(tmp_path, "echo localhost:2")
        latch = str(tmp_path / "flip.latch")
        env = make_env(tmp_path, steps=10, sleep=0.1)
        env["HOROVOD_FAULTS"] = \
            f"numerics.param:flip:at=4,rank=1,once={latch}"
        env["HOROVOD_FAULTS_SEED"] = "7"
        env["HOROVOD_NUMERICS_CHECK_EVERY"] = "2"
        env["HOROVOD_LOG_LEVEL"] = "info"
        p = launch(script, env, extra=("--reset-limit", "3"))
        out, _ = p.communicate(timeout=420)
        _skip_if_no_multiproc(out, p.returncode)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) == 2, (lines, out)
        assert "faults: firing flip at numerics.param" in out, out
        assert os.path.exists(latch), "flip latch never created"
        assert "replica divergence" in out, out
        assert "divergent ranks [1]" in out, out
        # recovered through the elastic restore path, not a crash
        assert "restoring" in out, out
        assert "worker failure" not in out, out
