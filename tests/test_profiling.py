"""XPlane parser + time-attribution digest (horovod_tpu/profiling.py).

The committed fixture `tests/data/tiny_trace.xplane.pb` is a
SYNTHETIC TPU-shaped XSpace (device plane + XLA Ops line + host
executor line + an ignored python line) built by `_build_fixture()`
below — synthesized, because this CPU container cannot capture a TPU
device plane, and the parser must be pinned against the TPU shape it
will meet on silicon. Three things are pinned byte-exactly:

  * the fixture bytes themselves (encoder drift shows up as a diff),
  * the parsed digest vs `tests/data/tiny_trace_golden.json`,
  * digest determinism (same bytes -> same JSON, twice).

The end-to-end smoke captures a REAL `jax.profiler` trace of a toy
jitted model through `profiling.capture` and digests it — the same
path `bench.py --profile` drives — inside the tier-1 budget.
"""

import json
import os
import struct
import subprocess
import sys

import pytest

from horovod_tpu import profiling

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
FIXTURE = os.path.join(DATA_DIR, "tiny_trace.xplane.pb")
GOLDEN = os.path.join(DATA_DIR, "tiny_trace_golden.json")


# ---------------------------------------------------------------------------
# Minimal protobuf wire ENCODER (test-only; the module ships only the
# decoder) — enough to synthesize an XSpace.
# ---------------------------------------------------------------------------

def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(fnum: int, v: int) -> bytes:
    return _varint(fnum << 3 | 0) + _varint(v)


def _field_bytes(fnum: int, payload: bytes) -> bytes:
    return _varint(fnum << 3 | 2) + _varint(len(payload)) + payload


def _field_str(fnum: int, s: str) -> bytes:
    return _field_bytes(fnum, s.encode())


def _event(metadata_id: int, offset_ps: int, dur_ps: int) -> bytes:
    return (_field_varint(1, metadata_id)
            + _field_varint(2, offset_ps)
            + _field_varint(3, dur_ps))


def _line(name: str, timestamp_ns: int, events) -> bytes:
    payload = _field_str(2, name) + _field_varint(3, timestamp_ns)
    for ev in events:
        payload += _field_bytes(4, _event(*ev))
    return payload


def _event_metadata(mid: int, name: str) -> bytes:
    # map<int64, XEventMetadata> entry: key=1, value=2
    meta = _field_varint(1, mid) + _field_str(2, name)
    return _field_varint(1, mid) + _field_bytes(2, meta)


def _plane(name: str, metadata, lines) -> bytes:
    payload = _field_str(2, name)
    for raw in lines:
        payload += _field_bytes(3, raw)
    for mid, mname in metadata:
        payload += _field_bytes(4, _event_metadata(mid, mname))
    return payload


def _build_fixture() -> bytes:
    """One TPU device plane (XLA Ops lane: dot / fusion / all-reduce /
    copy / convert, with a deliberate 1 us host gap) + the host plane
    (one executor lane whose scaffolding event must be excluded from
    per-op accounting, one python lane that must be ignored)."""
    device = _plane(
        "/device:TPU:0",
        metadata=[(1, "dot.5"), (2, "fusion.1"), (3, "all-reduce.1"),
                  (4, "copy.2"), (5, "convert.7")],
        lines=[_line("XLA Ops", 1000, [
            (1, 0, 2_000_000),           # dot: 2 us          (mxu)
            (2, 2_000_000, 1_000_000),   # fusion: 1 us       (vector)
            # 1 us gap here — the host_gap the digest must report
            (3, 4_000_000, 500_000),     # all-reduce: 0.5 us (coll.)
            (4, 4_500_000, 250_000),     # copy: 0.25 us      (copy)
            (5, 4_750_000, 250_000),     # convert: 0.25 us   (copy)
        ])])
    host = _plane(
        "/host:CPU",
        metadata=[(1, "ThunkExecutor::Execute"), (2, "reduce.3"),
                  (3, "$python_frame")],
        lines=[
            _line("tf_XLATfrtCpuClient/-42", 9_000_000, [
                (1, 0, 1_000_000),       # scaffolding: busy, not an op
                (2, 100_000, 400_000),   # reduce: 0.4 us     (vector)
            ]),
            _line("python", 9_000_000, [(3, 0, 5_000_000)]),
        ])
    return _field_bytes(1, device) + _field_bytes(1, host)


# ---------------------------------------------------------------------------
# Fixture + golden pins
# ---------------------------------------------------------------------------

def test_committed_fixture_matches_encoder():
    with open(FIXTURE, "rb") as f:
        assert f.read() == _build_fixture(), \
            "tests/data/tiny_trace.xplane.pb no longer matches " \
            "_build_fixture(); regenerate BOTH fixture and golden"


def test_breakdown_matches_committed_golden():
    with open(FIXTURE, "rb") as f:
        digest = profiling.breakdown(f.read(), top=5)
    with open(GOLDEN) as f:
        want = json.load(f)
    assert digest == want, json.dumps(digest, indent=1, sort_keys=True)


def test_breakdown_byte_deterministic():
    data = _build_fixture()
    a = json.dumps(profiling.breakdown(data), sort_keys=True)
    b = json.dumps(profiling.breakdown(data), sort_keys=True)
    assert a == b


def test_fixture_semantics():
    """The numbers the golden encodes, asserted as semantics so a
    legitimate golden regeneration still has to satisfy them."""
    d = profiling.breakdown(_build_fixture())
    cats = d["categories"]
    assert cats["mxu"]["time_s"] == pytest.approx(2e-6)
    assert cats["collective"]["time_s"] == pytest.approx(0.5e-6)
    assert cats["copy_reshape"]["time_s"] == pytest.approx(0.5e-6)
    # vector = fusion (1 us) + host reduce (0.4 us); the executor
    # scaffolding event is NOT an op
    assert cats["vector"]["time_s"] == pytest.approx(1.4e-6)
    # the deliberate 1 us hole in the device lane, plus the host
    # lane's 8 ms standoff between the two planes' windows
    assert d["host_gap_s"] > 0
    assert d["top_sinks"][0]["name"] == "dot.5"
    assert d["top_sinks"][0]["category"] == "mxu"
    assert d["source_planes"] == ["/device:TPU:0", "/host:CPU"]


# ---------------------------------------------------------------------------
# Parser / categorizer units
# ---------------------------------------------------------------------------

def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2 ** 32, 2 ** 56 + 17):
        buf = _varint(v)
        got, idx = profiling._read_varint(buf, 0)
        assert got == v and idx == len(buf)


def test_unknown_fields_skipped():
    # A message with an extra fixed64 field the schema doesn't know
    # must parse (forward compatibility with XPlane schema growth).
    extra = _varint(99 << 3 | 1) + struct.pack("<Q", 7)
    plane = _plane("/device:TPU:0", [(1, "dot.1")],
                   [_line("XLA Ops", 0, [(1, 0, 10)])])
    data = _field_bytes(1, plane + extra)
    space = profiling.parse_xspace(data)
    assert space["planes"][0]["name"] == "/device:TPU:0"


@pytest.mark.parametrize("name,want", [
    ("dot.17", "mxu"),
    ("%convolution.3", "mxu"),
    ("loop_convolution_fusion.2", "mxu"),
    ("convert.1318", "copy_reshape"),       # NOT mxu: convert != conv
    ("loop_convert_fusion", "copy_reshape"),
    ("copy-start.1", "copy_reshape"),
    ("transpose.9", "copy_reshape"),
    ("all-reduce-start.1", "collective"),
    ("all-gather.2", "collective"),         # not eaten by "gather"
    ("gather.4", "copy_reshape"),
    ("collective-permute-done.1", "collective"),
    ("reduce.8", "vector"),
    ("reduce-window.1", "vector"),
    ("fusion.130", "vector"),
    ("infeed.1", "infeed_outfeed"),
])
def test_categorize(name, want):
    assert profiling.categorize(name) == want


def test_digest_trace_missing_capture_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        profiling.digest_trace(str(tmp_path))


def test_profile_digest_block_shape():
    with open(FIXTURE, "rb") as f:
        data = f.read()
    # route through a fake trace-dir layout
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        run = os.path.join(td, "plugins", "profile", "2026_01_01")
        os.makedirs(run)
        with open(os.path.join(run, "host.xplane.pb"), "wb") as f:
            f.write(data)
        block = profiling.profile_digest_block(td, top=3)
    assert len(block["top_sinks"]) == 3
    assert set(block["categories"]) == {
        "mxu", "vector", "copy_reshape", "collective", "host_gap"}
    assert block["xplane"] == "host.xplane.pb"


def test_sink_table_md_renders():
    with open(FIXTURE, "rb") as f:
        digest = profiling.breakdown(f.read())
    md = profiling.sink_table_md(digest)
    assert "| 1 | `dot.5` | mxu |" in md
    assert "Category split:" in md


# ---------------------------------------------------------------------------
# End-to-end smoke: real capture -> digest (the bench --profile path)
# ---------------------------------------------------------------------------

def test_capture_toy_model_end_to_end(tmp_path):
    """profiling.capture around a toy jitted train-ish step, then the
    digest — the exact pipeline bench.py --profile runs, on a model
    small enough for the tier-1 budget."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(w, x):
        h = jnp.tanh(x @ w)
        return w - 0.1 * jax.grad(
            lambda w: jnp.sum((x @ w - h) ** 2))(w)

    w = jnp.ones((128, 128), jnp.float32)
    x = jnp.ones((32, 128), jnp.float32)
    w = step(w, x)
    jax.block_until_ready(w)
    with profiling.capture(str(tmp_path)):
        for _ in range(3):
            w = step(w, x)
        jax.block_until_ready(w)
    digest = profiling.digest_trace(str(tmp_path))
    assert digest["op_time_s"] > 0
    assert digest["categories"]["mxu"]["time_s"] > 0, digest
    assert digest["top_sinks"], digest
    # the compact block bench.py embeds
    block = profiling.profile_digest_block(str(tmp_path))
    assert "error" not in block and block["top_sinks"]


@pytest.mark.slow
def test_bench_profile_cli(tmp_path):
    """Full CLI: bench.py --profile on the reduced model emits a JSON
    artifact whose profile block carries top-3 sinks and the schema's
    mfu/compiled_gflop_per_img keys."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_RESNET_STAGES="1",
               BENCH_BATCH="4", BENCH_IMAGE="32", BENCH_STEPS="4",
               BENCH_WARMUP="1", BENCH_PROFILE=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py"), "--profile"],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "mfu" in doc and "compiled_gflop_per_img" in doc
    assert doc["profile"]["top_sinks"]
    assert len(doc["profile"]["top_sinks"]) <= 3
