"""Journal-chaos elastic worker: the seeded soak behind
benchmarks/INCIDENT_chaos_r11.json.

Like tests/elastic_worker.py but deliberately CONTROL-PLANE ONLY: the
state broadcast is an identity function and no data-plane collective
runs, so the full elastic lifecycle (rendezvous, heartbeats, commit
snapshots, gang restarts, the journal) exercises on jaxlib builds
whose CPU backend cannot run cross-process collectives — the exact
container the committed incident artifact is generated in. The
committed-step watermark still measures real recovery semantics:
rank 0's pickle snapshot is the durable commit, and the journal's
durable-commit events are what `doctor incident` accounts loss
against.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

LOG = os.environ.get("ELASTIC_TEST_LOG", "/tmp/journal_chaos")
TOTAL_STEPS = int(os.environ.get("ELASTIC_TEST_STEPS", "18"))
STEP_SLEEP = float(os.environ.get("ELASTIC_TEST_SLEEP", "0.2"))


def log_line(msg):
    with open(f"{LOG}.{os.environ.get('HOROVOD_RANK', '?')}", "a") as f:
        f.write(msg + "\n")


# File-based lockstep pacing: with no data-plane collective to gate
# on, a healthy rank would race arbitrarily far ahead of a crashed or
# hung peer (and rank 0 could even finish the job while the peer is
# parked, turning the hang into a clean completion instead of a
# detected recovery). Each rank publishes its committed step; nobody
# starts step N+1 until every peer has committed N — the same
# lockstep a real allreduce enforces, built from the shared
# filesystem this single-host soak runs on.

def _publish_step(rank, step):
    tmp = f"{LOG}.pace.{rank}.tmp"
    with open(tmp, "w") as f:
        f.write(str(step))
    os.replace(tmp, f"{LOG}.pace.{rank}")


def _peer_floor(world, me):
    floor = None
    for r in range(world):
        if r == me:
            continue
        try:
            with open(f"{LOG}.pace.{r}") as f:
                v = int(f.read().strip() or "0")
        except (OSError, ValueError):
            v = 0
        floor = v if floor is None else min(floor, v)
    return floor if floor is not None else 1 << 30


def _pace_wait(state):
    me, world = hvd.rank(), hvd.size()
    while _peer_floor(world, me) < int(state.step) - 1:
        time.sleep(0.05)


def main():
    hvd.init()
    # params=None keeps JaxState.sync off the data-plane broadcast;
    # the weights live as a plain ObjectState attr and the identity
    # bcast_object keeps sync() collective-free (see docstring).
    state = hvd.elastic.JaxState(
        params=None, step=0, w=np.zeros((2,)),
        snapshot_path=f"{LOG}_snapshot.bin",
        snapshot_backend="pickle",
        bcast_object=lambda obj, root_rank=0: obj)

    @hvd.elastic.run
    def train(state):
        # (Re)entering the loop — fresh spawn, gang restart, or
        # resize — republish this rank's position first: a rank that
        # sat out a partial-world period (whole-slice blacklist)
        # otherwise leaves a stale pace file every peer would wait on
        # forever once it rejoins.
        _publish_step(hvd.rank(), int(state.step))
        while state.step < TOTAL_STEPS:
            _pace_wait(state)
            # one "training step": local-only compute (no cross-
            # process collective — see module docstring)
            state.w = state.w + 1.0
            state.step += 1
            log_line(f"step {state.step} world {hvd.size()} "
                     f"rank {hvd.rank()}")
            state.check_host_updates()
            state.commit()
            _publish_step(hvd.rank(), int(state.step))
            time.sleep(STEP_SLEEP)

    train(state)
    log_line(f"done world {hvd.size()} rank {hvd.rank()} "
             f"step {int(state.step)}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
