"""make_pipelined_step: the apply-then-grad fusion must be
MATHEMATICALLY IDENTICAL to the classic grad/reduce/apply loop (only
the program boundaries move — step i still computes grads on params
that absorbed grads i-1), and finalize() must flush the pending
grads. See horovod_tpu/optim/pipelined.py for the TPU rationale."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def _problem():
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(64, 8).astype(np.float32))
    y = jnp.asarray(rng.randn(64).astype(np.float32))
    params = {"w": jnp.zeros((8,)), "b": jnp.zeros(())}

    def loss_fn(p, batch):
        xb, yb = batch
        pred = xb @ p["w"] + p["b"]
        return jnp.mean((pred - yb) ** 2)

    batches = [(X[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
               for i in range(4)] * 2
    return loss_fn, params, batches


class TestPipelinedStep:
    def test_matches_classic_loop(self, hvd_single):
        hvd = hvd_single
        loss_fn, params, batches = _problem()
        opt = optax.adam(0.05)

        # classic: grad -> grouped_allreduce -> apply
        p_ref = jax.tree_util.tree_map(jnp.copy, params)
        s_ref = opt.init(p_ref)
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        losses_ref = []
        for b in batches:
            loss, g = grad_fn(p_ref, b)
            leaves, td = jax.tree_util.tree_flatten(g)
            red = hvd.grouped_allreduce(leaves, op=hvd.Average)
            g = jax.tree_util.tree_unflatten(td, red)
            up, s_ref = opt.update(g, s_ref, p_ref)
            p_ref = optax.apply_updates(p_ref, up)
            losses_ref.append(float(loss))

        # pipelined: one fused apply+grad program per step
        step = hvd.make_pipelined_step(loss_fn, opt, op=hvd.Average)
        p2 = jax.tree_util.tree_map(jnp.copy, params)
        state = step.init(p2, opt.init(p2), batches[0])
        losses = []
        for b in batches[1:]:
            state, loss = step(state, b)
            losses.append(float(loss))
        p_fin, _ = step.finalize(state)

        # loss at init()/step(i) is computed BEFORE applying that
        # batch's grads, so the sequences align shifted by the carry:
        # pipelined losses[i] == classic losses[i+1]'s pre-update loss
        # on the same params trajectory. After finalize, params match
        # the classic loop that consumed the same batches.
        np.testing.assert_allclose(losses, losses_ref[1:], rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(p_fin),
                        jax.tree_util.tree_leaves(p_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_has_aux(self, hvd_single):
        hvd = hvd_single
        loss_fn, params, batches = _problem()

        def loss_aux(p, batch):
            loss = loss_fn(p, batch)
            return loss, {"twice": loss * 2}

        opt = optax.sgd(0.1)
        step = hvd.make_pipelined_step(loss_aux, opt, op=hvd.Average,
                                       has_aux=True)
        state = step.init(params, opt.init(params), batches[0])
        state, (loss, aux) = step(state, batches[1])
        np.testing.assert_allclose(float(aux["twice"]),
                                   2 * float(loss), rtol=1e-6)

    def test_compression_rides_the_wire(self, hvd_single):
        hvd = hvd_single
        loss_fn, params, batches = _problem()
        opt = optax.sgd(0.1)
        step = hvd.make_pipelined_step(
            loss_fn, opt, op=hvd.Average,
            compression=hvd.Compression.fp16)
        state = step.init(params, opt.init(params), batches[0])
        state, loss = step(state, batches[1])
        assert np.isfinite(float(loss))
        p, _ = step.finalize(state)
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree_util.tree_leaves(p))
