"""Negotiated-controller tests: single-process native/python cores
in-proc, plus real multi-process negotiation via the launcher
(reference: the horovodrun-under-pytest strategy, SURVEY.md §4)."""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(params=["native", "python"])
def hvd_ctrl(request):
    """hvd initialized single-process with a forced controller."""
    import horovod_tpu as hvd
    from horovod_tpu.core import native
    if request.param == "native" and not native.available():
        pytest.skip("native core not built")
    hvd.init(config_overrides={"HOROVOD_CONTROLLER": request.param})
    yield hvd
    hvd.shutdown()


class TestControllerSingleProcess:
    def test_controller_active(self, hvd_ctrl):
        from horovod_tpu.common.basics import state
        assert state().engine.controller is not None

    def test_allreduce_roundtrip(self, hvd_ctrl):
        out = hvd_ctrl.allreduce(jnp.arange(6.0), name="c0")
        np.testing.assert_allclose(np.asarray(out), np.arange(6.0))

    def test_grouped_keeps_list(self, hvd_ctrl):
        outs = hvd_ctrl.grouped_allreduce([jnp.ones(3)], name="c1")
        assert isinstance(outs, list) and len(outs) == 1

    def test_mixed_dtype_group(self, hvd_ctrl):
        outs = hvd_ctrl.grouped_allreduce(
            [jnp.ones(3, jnp.float32), jnp.ones(2, jnp.float64),
             jnp.ones(4, jnp.float32)],
            op=hvd_ctrl.Sum, name="c2")
        assert [o.dtype for o in outs] == [jnp.float32, jnp.float64,
                                           jnp.float32]
        for o in outs:
            np.testing.assert_allclose(np.asarray(o), 1.0)

    def test_generic_ops_via_controller(self, hvd_ctrl):
        out = hvd_ctrl.broadcast(jnp.arange(4.0), root_rank=0,
                                 name="c3")
        np.testing.assert_allclose(np.asarray(out), np.arange(4.0))
        out = hvd_ctrl.allgather(jnp.ones((2, 2)), name="c4")
        assert out.shape == (2, 2)
        hvd_ctrl.barrier()

    def test_join_single(self, hvd_ctrl):
        assert hvd_ctrl.join() == 0

    def test_duplicate_pending_name(self, hvd_ctrl):
        """Names must be unique among IN-FLIGHT ops: a duplicate while
        the first is pending errors; once the first completed, the
        name is free again (so either outcome is a correct run,
        depending on worker timing)."""
        h1 = hvd_ctrl.allreduce_async(jnp.ones(2), name="dup")
        h2 = hvd_ctrl.allreduce_async(jnp.ones(2), name="dup")
        np.testing.assert_allclose(
            np.asarray(hvd_ctrl.synchronize(h1)), 1.0)
        try:
            out = hvd_ctrl.synchronize(h2)
            np.testing.assert_allclose(np.asarray(out), 1.0)
        except ValueError as e:
            assert "already pending" in str(e)

    def test_composition_churn_warning(self, hvd_ctrl):
        """>16 distinct fused-batch compositions with quiescence off
        must warn once, naming HOROVOD_BATCH_QUIESCENCE (every new
        composition is a fresh compiled XLA program — the measured
        eager slowdown mode, docs/benchmarks.md). The hvd logger has
        propagate=False, so capture with an attached handler."""
        import logging
        from horovod_tpu.common.logging import logger

        records = []

        class Grab(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        h = Grab(level=logging.WARNING)
        logger.addHandler(h)
        try:
            for i in range(20):
                # unique shape per op -> unique composition
                hvd_ctrl.allreduce(jnp.ones(3 + i), name=f"churn{i}")
        finally:
            logger.removeHandler(h)
        hits = [m for m in records if "HOROVOD_BATCH_QUIESCENCE" in m]
        assert len(hits) == 1, records

    def test_compression_roundtrip(self, hvd_ctrl):
        from horovod_tpu.ops.compression import Compression
        x = jnp.arange(8.0, dtype=jnp.float32)
        out = hvd_ctrl.allreduce(x, name="c5",
                                 compression=Compression.fp16)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0),
                                   rtol=1e-3)


class TestWireDtypeFusion:
    """Fusion keys on the WIRE dtype: raw dtypes that compress to one
    wire dtype (bf16 weights + f32 norms under fp16 compression)
    submit as ONE entry and execute as ONE fused batch — a deliberate
    improvement on the reference's same-raw-dtype FuseResponses rule
    (the casts fold into the fused XLA kernel for free). Without
    compression the wires differ and the split is preserved."""

    @pytest.fixture
    def hvd_native(self):
        import horovod_tpu as hvd
        from horovod_tpu.core import native
        if not native.available():
            pytest.skip("native core not built")
        hvd.init(config_overrides={"HOROVOD_CONTROLLER": "native"})
        yield hvd
        hvd.shutdown()

    def counts(self, kind="ar"):
        from horovod_tpu.common.basics import state
        return list(state().engine.controller.exec_counts.get(
            kind, [0, 0]))

    def test_mixed_raw_same_wire_is_one_batch(self, hvd_native):
        import jax.numpy as jnp
        before = self.counts()
        outs = hvd_native.grouped_allreduce(
            [jnp.full((1024,), 2.0, jnp.bfloat16),
             jnp.full((64,), 3.0, jnp.float32)],
            op=hvd_native.Sum,
            compression=hvd_native.Compression.fp16, name="wirefuse")
        after = self.counts()
        assert after[0] - before[0] == 1, (before, after)  # 1 batch
        assert after[1] - before[1] == 1, (before, after)  # 1 entry
        assert outs[0].dtype == jnp.bfloat16
        assert outs[1].dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(outs[0], np.float32),
                                   np.full(1024, 2.0), rtol=1e-2)
        np.testing.assert_allclose(np.asarray(outs[1]),
                                   np.full(64, 3.0), rtol=1e-3)

    def test_mixed_wire_still_splits(self, hvd_native):
        import jax.numpy as jnp
        before = self.counts()
        outs = hvd_native.grouped_allreduce(
            [jnp.full((128,), 2.0, jnp.bfloat16),
             jnp.full((64,), 3.0, jnp.float32)],
            op=hvd_native.Sum, name="wiresplit")
        after = self.counts()
        assert after[0] - before[0] == 2, (before, after)  # 2 batches
        assert outs[0].dtype == jnp.bfloat16
        assert outs[1].dtype == jnp.float32

    def test_fail_batch_trace_stays_balanced(self, hvd_native, tmp_path):
        """fail_batch on a never-dispatched pending entry must close
        its open QUEUE span (tl.error), not emit an unmatched
        DISPATCH end — the Chrome trace stays well-formed."""
        import jax.numpy as jnp
        from horovod_tpu.common.basics import state
        from horovod_tpu.core import native
        from horovod_tpu.ops.controller import _PendingAllreduce
        from horovod_tpu.ops.compression import NoneCompressor

        path = str(tmp_path / "fail.json")
        hvd_native.start_timeline(path)
        st = state()
        ctl = st.engine.controller
        tl = st.engine.timeline
        pset = st.process_set_table.global_set
        h = st.engine.new_handle("doomed")
        # Mimic the post-agreement state for a local entry: QUEUE span
        # open (controller opens it right before the execute call),
        # entry still pending, never dispatched.
        tl.enqueue("doomed")
        with ctl._mu:
            ctl._pending["doomed"] = _PendingAllreduce(
                [jnp.ones(4)], NoneCompressor, pset, 0, 1.0, 1.0, h,
                True)
        bad = native.BatchEntry("doomed", "ar|not|a|sig", 1, "", 0, "")
        ctl._execute_allreduce_batch([bad])   # must not raise
        with pytest.raises(RuntimeError, match="malformed"):
            hvd_native.synchronize(h.id)
        hvd_native.stop_timeline()
        events = json.load(open(path))
        opens = {}
        for e in events:
            key = (e.get("tid"), e["name"])
            if e["ph"] == "B":
                opens[key] = opens.get(key, 0) + 1
            elif e["ph"] == "E":
                opens[key] = opens.get(key, 0) - 1
        assert all(v == 0 for v in opens.values()), opens

    def test_malformed_sig_errors_batch_not_worker(self, hvd_native):
        """A malformed agreed signature (mixed-version peer) must
        degrade to per-batch errors — the dispatch worker survives
        and subsequent collectives still complete."""
        import jax.numpy as jnp
        from horovod_tpu.common.basics import state
        from horovod_tpu.core import native
        ctl = state().engine.controller
        bad = native.BatchEntry("ghost", "ar|not|a|sig", 1, "", 0, "")
        ctl._execute_allreduce_batch([bad])   # must not raise
        out = hvd_native.allreduce(jnp.ones(4), name="after_bad")
        np.testing.assert_allclose(np.asarray(out), np.ones(4))


class TestPythonCoreDivergence:
    """The PythonCore's documented divergences from the C++ core
    (PythonCore docstring: no cross-rank mismatch checks, so no error
    entries ever) must stay INTENTIONAL — this pins both the
    divergence and the guard that keeps it acceptable (python core
    refuses multi-process), per round-4 verdict weak #5."""

    def test_entries_never_carry_errors(self):
        from horovod_tpu.ops.controller import PythonCore
        core = PythonCore(fusion_threshold=1 << 20)
        core.submit("t1", "ar|float32|0|1.0|1.0#4", 1024)
        core.submit("t2", "ar|float32|0|1.0|1.0#8", 2048)
        batch = core.next_batch(1.0)
        assert batch and all(e.error == "" for e in batch), \
            "PythonCore grew error entries — if mismatch checking " \
            "was added in-process, update the documented divergence"

    def test_python_core_refuses_multiprocess(self):
        """The guard that makes the divergence safe: with size > 1
        the python controller must refuse loudly, not negotiate
        wrongly in-process."""
        import horovod_tpu as hvd
        from horovod_tpu.common import basics
        orig = basics.detect  # basics early-binds the symbol

        def fake_detect(cfg):
            t = orig(cfg)
            t.size = 2
            return t

        basics.detect = fake_detect
        try:
            with pytest.raises(RuntimeError, match="single-process"):
                hvd.init(config_overrides={
                    "HOROVOD_CONTROLLER": "python"})
        finally:
            basics.detect = orig
            try:
                hvd.shutdown()
            except Exception:
                pass


class TestNativeCoreUnit:
    """Drive the C ABI directly (reference: C++ unit coverage of
    controller.cc)."""

    def setup_method(self, _):
        from horovod_tpu.core import native
        if not native.available():
            pytest.skip("native core not built")

    def make_core(self, **kw):
        from horovod_tpu.core.native import NativeCore
        args = dict(rank=0, size=1, coord_host="127.0.0.1",
                    coord_port=0, fusion_threshold=1 << 20,
                    cycle_time_ms=1.0, stall_warn_s=0.0,
                    stall_kill_s=0.0)
        args.update(kw)
        return NativeCore(**args)

    def test_fusion_packs_same_key(self):
        core = self.make_core()
        for i in range(4):
            core.submit(f"t{i}", "ar|f32|1|0|1.0|1.0#8", 32)
        batch = []
        deadline = 50
        while len(batch) < 4 and deadline:
            b = core.next_batch(0.2)
            assert b is not None
            batch += b
            deadline -= 1
        names = [e.name for e in batch]
        assert names == ["t0", "t1", "t2", "t3"]
        core.shutdown()
        core.destroy()

    def test_fusion_threshold_splits(self):
        core = self.make_core(fusion_threshold=64)
        # 3 x 48 bytes: 48+48 > 64 so at most one per batch
        for i in range(3):
            core.submit(f"s{i}", "ar|f32|1|0|1.0|1.0#12", 48)
        batches = []
        got = 0
        while got < 3:
            b = core.next_batch(0.3)
            assert b is not None
            if b:
                batches.append([e.name for e in b])
                got += len(b)
        assert all(len(b) == 1 for b in batches), batches
        core.shutdown()
        core.destroy()

    def test_key_change_breaks_batch(self):
        core = self.make_core()
        core.submit("a", "ar|f32|1|0|1.0|1.0#4", 16)
        core.submit("b", "ar|f64|1|0|1.0|1.0#4", 32)
        seen = []
        while len(seen) < 2:
            b = core.next_batch(0.3)
            assert b is not None
            if b:
                seen.append([e.name for e in b])
        assert seen == [["a"], ["b"]]
        core.shutdown()
        core.destroy()

    def test_shutdown_unblocks(self):
        core = self.make_core()
        core.shutdown()
        assert core.next_batch(5.0) is None
        core.destroy()

    def test_quiescence_storm_cuts_one_batch(self):
        """HOROVOD_BATCH_QUIESCENCE: a trickling submission storm
        (gaps >> cycle time) must agree as ONE fused batch — the
        coordinator holds the cut while the ready set still grows, so
        the batch composition (= the compiled XLA program) is stable
        step over step instead of ragged."""
        import time
        core = self.make_core(cycle_time_ms=1.0)
        core.set_quiescence(5)
        for i in range(8):
            core.submit(f"q{i}", "ar|f32|1|0|1.0|1.0#8", 32)
            time.sleep(0.004)  # 4x the cycle: would split without
        batches = []
        got = 0
        while got < 8:
            b = core.next_batch(0.3)
            assert b is not None
            if b:
                batches.append([e.name for e in b])
                got += len(b)
        assert batches == [[f"q{i}" for i in range(8)]], batches
        core.shutdown()
        core.destroy()

    def test_submit_after_shutdown_fails_fast(self):
        """Ops submitted after the dispatch worker exited must error
        immediately with HorovodInternalError (the elastic-resize
        wedge: a survivor's next collective would otherwise wait
        forever on a control plane that already closed)."""
        import time
        import horovod_tpu as hvd
        from horovod_tpu.common.basics import state
        from horovod_tpu.common.exceptions import HorovodInternalError
        hvd.init(config_overrides={"HOROVOD_CONTROLLER": "native"})
        try:
            ctl = state().engine.controller
            # out-of-band core shutdown (what a coordinator loss looks
            # like); wait for the worker loop to reach terminal state
            ctl.core.shutdown()
            deadline = time.time() + 10
            while ctl._terminated is None and time.time() < deadline:
                time.sleep(0.02)
            assert ctl._terminated is not None
            h = hvd.allreduce_async(jnp.ones(3), name="late")
            with pytest.raises(HorovodInternalError):
                hvd.synchronize(h)
        finally:
            hvd.shutdown()

    def test_quiescence_python_core(self):
        """PythonCore analog of the quiescence gate."""
        import threading
        import time
        from horovod_tpu.ops.controller import PythonCore
        core = PythonCore(1 << 20, cycle_time_ms=1.0)
        core.set_quiescence(5)

        def storm():
            for i in range(8):
                core.submit(f"p{i}", "ar|f32|1|0|1.0|1.0#8", 32)
                time.sleep(0.004)

        t = threading.Thread(target=storm)
        t.start()
        batch = core.next_batch(5.0)
        t.join()
        assert [e.name for e in batch] == [f"p{i}" for i in range(8)]
        core.shutdown()

    def test_buffer_grow_keeps_batch(self):
        """A batch bigger than the ctypes buffer must survive the
        regrow-and-retry — the core serializes before consuming
        (peek-then-pop), so nothing is dropped (round-1 advisory:
        c_api.cc popped before the bufsize check)."""
        import ctypes
        core = self.make_core()
        core.BUF_SIZE = 16  # force the too-small path
        core._buf = ctypes.create_string_buffer(16)
        long_name = "x" * 200
        core.submit(long_name, "ar|f32|1|0|1.0|1.0#8", 32)
        got = []
        deadline = 50
        while not got and deadline:
            b = core.next_batch(0.2)
            assert b is not None
            got += b
            deadline -= 1
        assert [e.name for e in got] == [long_name]
        assert core.BUF_SIZE > 16  # grew to fit
        core.shutdown()
        core.destroy()

    def test_set_cycle_time_changes_rate(self):
        """Tuned cycle time must actually pace the core's loop
        (round-1 verdict: half the autotune search space was dead)."""
        import time
        core = self.make_core(cycle_time_ms=200.0)
        time.sleep(0.6)
        slow = core.cycles()
        assert slow <= 10, slow
        core.set_cycle_time(1.0)
        time.sleep(0.8)  # let the in-flight 200ms sleep drain
        base = core.cycles()
        time.sleep(0.6)
        fast = core.cycles() - base
        assert fast > 5 * max(slow, 1), (slow, fast)
        core.shutdown()
        core.destroy()

    def test_cache_capacity_zero_disables(self):
        core = self.make_core(cache_capacity=0)
        core.submit("nc", "ar|f32|1|0|1.0|1.0#4", 16)
        got = []
        deadline = 50
        while not got and deadline:
            b = core.next_batch(0.2)
            assert b is not None
            got += b
            deadline -= 1
        assert got[0].name == "nc"
        core.shutdown()
        core.destroy()

    def test_negotiate_us_on_entries(self):
        """The submit->agreed duration field survives the C ABI batch
        encoding as an int (the nonzero multi-rank case is asserted in
        the 2-proc timeline phase of mp_worker_negotiation.py)."""
        core = self.make_core()
        core.submit("tm", "ar|f32|1|0|1.0|1.0#4", 16)
        got = []
        deadline = 50
        while not got and deadline:
            b = core.next_batch(0.2)
            assert b is not None
            got += b
            deadline -= 1
        assert got and isinstance(got[0].negotiate_us, int)
        core.shutdown()
        core.destroy()


@pytest.mark.integration
class TestNegotiationMultiProcess:
    @pytest.mark.parametrize("np_", [2, 4])
    def test_negotiation(self, np_, multiproc_data_plane):
        # multiproc_data_plane: the worker runs real eager allreduces
        # whose DISPATCH needs cross-process XLA collectives — absent
        # on this image's jaxlib CPU backend (negotiation itself is
        # covered without that backend by test_tree_wiring below and
        # the C++ harnesses).
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np",
             str(np_), sys.executable,
             os.path.join("tests", "mp_worker_negotiation.py")],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=240)
        assert r.returncode == 0, r.stdout + "\n" + r.stderr
        assert r.stdout.count("NEGOTIATION ALL OK") == np_


@pytest.mark.integration
def test_eager_cache_microbench_traffic_ratio(multiproc_data_plane):
    """The benchmarks/ microbench's headline claim, asserted: the
    response cache shrinks steady-state control traffic severalfold
    (reference: response_cache.cc's bit-vector motivation; here
    5-byte id announcements). Gated on the mp data plane (the
    microbench job runs 2-proc eager allreduces) AND on a quiet box:
    its per-iteration byte ratio is deterministic, but the 2x200-iter
    subprocess jobs stall into their timeouts when the host is
    already saturated."""
    if os.getloadavg()[0] > 4 * (os.cpu_count() or 1):
        pytest.skip(f"box too loaded for the timed microbench "
                    f"(load {os.getloadavg()[0]:.1f} on "
                    f"{os.cpu_count()} cpus)")
    import importlib.util
    import os as _os
    spec = importlib.util.spec_from_file_location(
        "eager_cache_latency",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "benchmarks",
            "eager_cache_latency.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    on = mod.run_job(100, cache_capacity=1024)
    off = mod.run_job(100, cache_capacity=0)
    per_on = on["control_bytes"] / (on["iters"] + mod.WARMUP)
    per_off = off["control_bytes"] / (off["iters"] + mod.WARMUP)
    assert per_off > 2 * per_on, (per_on, per_off)
