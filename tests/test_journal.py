"""Journal crash-semantics + incident-analyzer tests: truncated-tail
repair, merge byte-determinism, MTTR decomposition on synthetic event
streams, the committed-step watermark across a simulated restart, the
committed chaos artifact's regeneration pin, and (behind the
multiproc probe) a live 2-rank chaos run whose incident report must
name the injected-fault rank."""

import json
import os
import subprocess
import sys
import time

import pytest

from horovod_tpu import journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT_DIR = os.path.join(REPO, "benchmarks", "incident_chaos_r11")
ARTIFACT = os.path.join(REPO, "benchmarks", "INCIDENT_chaos_r11.json")


@pytest.fixture
def jdir(tmp_path, monkeypatch):
    """Armed journal in a tmp dir; module state restored after."""
    d = str(tmp_path / "journal")
    monkeypatch.setenv("HOROVOD_JOURNAL_DIR", d)
    yield d
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None
    journal._first_commit_pending = None


def _reset_module():
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None
    journal._first_commit_pending = None


class TestWriter:
    def test_roundtrip_and_meta(self, jdir):
        j = journal.configure("worker", 3)
        j.event("commit", step=7, epoch=2, durable=True)
        j.event("fault_fired", point="elastic.step", action="crash")
        events, dropped = journal.read_journal(j.path)
        assert dropped == 0
        assert [e["type"] for e in events] == [
            "journal_meta", "commit", "fault_fired"]
        meta = events[0]
        assert meta["schema"] == journal.SCHEMA
        assert meta["role"] == "worker" and meta["rank"] == 3
        assert "anchor_mono_ns" in meta and "anchor_unix" in meta
        c = events[1]
        assert c["step"] == 7 and c["durable"] is True
        # per-segment sequence + derived wall clock on every record
        assert [e["n"] for e in events] == [0, 1, 2]
        assert events[1]["t"] <= events[2]["t"]
        _reset_module()

    def test_truncated_tail_repair(self, jdir):
        """A SIGKILL mid-write leaves a torn last line; every intact
        record before it must survive the read."""
        j = journal.configure("worker", 0)
        for s in range(5):
            j.event("commit", step=s, epoch=1)
        _reset_module()
        path = os.path.join(jdir, "journal-rank0.jsonl")
        with open(path, "a") as f:
            f.write('{"type":"commit","step":99,"t":1.0,"ro')  # torn
        events, dropped = journal.read_journal(path)
        assert dropped == 1
        steps = [e["step"] for e in events if e["type"] == "commit"]
        assert steps == [0, 1, 2, 3, 4]
        # the torn step-99 record is GONE, not half-parsed
        assert 99 not in steps

    def test_rotation_keeps_two_segments(self, tmp_path, monkeypatch):
        d = str(tmp_path / "rot")
        monkeypatch.setenv("HOROVOD_JOURNAL_DIR", d)
        monkeypatch.setenv("HOROVOD_JOURNAL_ROTATE_MB", "1")
        j = journal.configure("worker", 0)
        j._rotate_bytes = 2048  # tiny cap for the test
        for s in range(64):
            j.event("commit", step=s, epoch=1)
        _reset_module()
        live = os.path.join(d, "journal-rank0.jsonl")
        rotated = live + ".1"
        assert os.path.exists(rotated), "no rotation happened"
        # both segments parse; the fresh one re-opens with a meta and
        # the merge reads rotated-then-live in write order
        ev_r, _ = journal.read_journal(rotated)
        ev_l, _ = journal.read_journal(live)
        assert ev_l[0]["type"] == "journal_meta"
        files = journal.find_journal_files(d)
        assert [os.path.basename(p) for p in files] == [
            "journal-rank0.jsonl.1", "journal-rank0.jsonl"]
        all_steps = [e["step"] for e in ev_r + ev_l
                     if e["type"] == "commit"]
        # two-segment bound by design: the oldest history is dropped,
        # but what remains is contiguous and ends at the newest step
        assert all_steps == list(range(all_steps[0], 64))
        assert len(all_steps) >= 16

    def test_disarmed_record_is_cheap_and_inert(self, tmp_path):
        _reset_module()
        assert not journal.enabled()
        t0 = time.perf_counter()
        for _ in range(100_000):
            journal.record("commit", step=1)
        dt = time.perf_counter() - t0
        # same contract as faults.fire disarmed: well under 1 us/call
        assert dt < 1.0, f"disarmed record too slow: {dt:.3f}s/100k"
        assert not list((tmp_path).glob("journal-*"))


class TestWatermark:
    def test_durable_commits_win(self, jdir):
        """A non-snapshot-writing rank running a step ahead must not
        inflate the watermark a restarted gang is held to."""
        os.makedirs(jdir, exist_ok=True)
        with open(os.path.join(jdir, "journal-rank0.jsonl"), "w") as f:
            for s in (1, 2, 3):
                f.write(json.dumps({"type": "commit", "step": s,
                                    "durable": True, "t": float(s),
                                    "role": "worker", "rank": 0,
                                    "n": s}) + "\n")
        with open(os.path.join(jdir, "journal-rank1.jsonl"), "w") as f:
            for s in (1, 2, 3, 4, 5):  # ahead, but nothing durable
                f.write(json.dumps({"type": "commit", "step": s,
                                    "t": float(s), "role": "worker",
                                    "rank": 1, "n": s}) + "\n")
        assert journal.watermark(jdir) == 3

    def test_plain_max_without_durable_flags(self, jdir):
        os.makedirs(jdir, exist_ok=True)
        with open(os.path.join(jdir, "journal-rank0.jsonl"), "w") as f:
            for s in (1, 2):
                f.write(json.dumps({"type": "commit", "step": s,
                                    "t": float(s), "role": "worker",
                                    "rank": 0, "n": s}) + "\n")
        assert journal.watermark(jdir) == 2
        assert journal.watermark(str(jdir) + "-nonexistent") == -1

    def test_note_sync_measures_loss_across_restart(self, jdir):
        """Simulated restart: incarnation 1 journals durable commits
        to step 5; the 'restarted' process resumes at 3 — note_sync
        must measure the 2-step loss and bump the SLO counter."""
        from horovod_tpu.metrics import REGISTRY
        j = journal.configure("worker", 0)
        for s in range(1, 6):
            j.event("commit", step=s, epoch=1, durable=True)
        # simulate the respawn: same dir, fresh journal module state
        _reset_module()
        journal.configure("worker", 0)
        before = REGISTRY.get(
            "hvd_committed_step_loss_total").value()
        journal.note_sync(3)
        after = REGISTRY.get("hvd_committed_step_loss_total").value()
        assert after - before == 2
        # the check itself is journaled, and a recovery is now
        # pending so the next commit closes first_commit
        events, _ = journal.read_journal(
            os.path.join(jdir, "journal-rank0.jsonl"))
        wm = [e for e in events if e["type"] == "watermark"]
        assert wm and wm[-1]["watermark"] == 5 \
            and wm[-1]["resumed"] == 3 and wm[-1]["loss"] == 2
        journal.note_commit(4, durable=True)
        events, _ = journal.read_journal(
            os.path.join(jdir, "journal-rank0.jsonl"))
        assert any(e["type"] == "first_commit" for e in events)
        _reset_module()

    def test_fresh_job_has_no_loss(self, jdir):
        from horovod_tpu.metrics import REGISTRY
        journal.configure("worker", 0)
        before = REGISTRY.get(
            "hvd_committed_step_loss_total").value()
        journal.note_sync(0)  # no prior commits anywhere
        assert REGISTRY.get(
            "hvd_committed_step_loss_total").value() == before
        _reset_module()


def _write_synthetic(dir_):
    """A synthetic crash recovery: rank 1 dies at t=10 inside an
    injected crash, detected at t=10.5, teardown to t=12, epoch 2
    published at t=12.25, respawned at t=12.5, both ranks synced by
    t=14, first epoch-2 commit at t=14.5."""
    os.makedirs(dir_, exist_ok=True)

    def w(name, recs):
        with open(os.path.join(dir_, name), "w") as f:
            for i, r in enumerate(recs):
                r.setdefault("n", i)
                f.write(json.dumps(r, sort_keys=True) + "\n")

    def ev(t, role, rank, type_, **kw):
        return dict(kw, t=t, role=role, rank=rank, type=type_)

    w("journal-driver.jsonl", [
        ev(0.0, "driver", -1, "journal_meta", schema=journal.SCHEMA,
           faults="elastic.step:crash:at=4,rank=1", faults_seed=7),
        ev(0.1, "driver", -1, "epoch_published", epoch=1, size=2,
           hosts={"0": "hostA", "1": "hostB"}),
        ev(0.2, "driver", -1, "spawn", exit_rank=0, host="hostA"),
        ev(0.2, "driver", -1, "spawn", exit_rank=1, host="hostB"),
        ev(0.3, "driver", -1, "respawn_done", epoch=1, ranks=2),
        ev(10.5, "driver", -1, "worker_exit", exit_rank=1,
           host="hostB", code=43),
        ev(10.5, "driver", -1, "detect", cause="crash", exit_rank=1,
           host="hostB", code=43, reset=1),
        ev(10.6, "driver", -1, "postmortem", exit_rank=1, code=43,
           file="postmortem-rank1.json", reason="crash", step=3),
        ev(10.7, "driver", -1, "blacklist", host="hostB",
           window_s=60.0, failures=1),
        ev(10.8, "driver", -1, "gang_restart_begin", reset=1,
           epoch=1),
        ev(12.0, "driver", -1, "teardown_done", reset=1),
        ev(12.25, "driver", -1, "epoch_published", epoch=2, size=2,
           hosts={"0": "hostA", "1": "hostA"}),
        ev(12.4, "driver", -1, "spawn", exit_rank=0, host="hostA"),
        ev(12.4, "driver", -1, "spawn", exit_rank=1, host="hostA"),
        ev(12.5, "driver", -1, "respawn_done", epoch=2, ranks=2),
        ev(20.0, "driver", -1, "job_done", code=0),
    ])
    w("journal-rank0.jsonl", [
        ev(0.5, "worker", 0, "journal_meta", schema=journal.SCHEMA),
        ev(0.6, "worker", 0, "init_done", epoch=1, world_size=2),
        ev(1.0, "worker", 0, "commit", step=1, epoch=1, durable=True),
        ev(5.0, "worker", 0, "commit", step=2, epoch=1, durable=True),
        ev(9.0, "worker", 0, "commit", step=3, epoch=1, durable=True),
        ev(13.0, "worker", 0, "init_done", epoch=2, world_size=2),
        ev(13.5, "worker", 0, "snapshot_loaded", step=3),
        ev(14.0, "worker", 0, "sync_done", step=3, epoch=2),
        ev(14.0, "worker", 0, "watermark", watermark=3, resumed=3,
           loss=0),
        ev(14.5, "worker", 0, "commit", step=4, epoch=2,
           durable=True),
    ])
    w("journal-rank1.jsonl", [
        ev(0.5, "worker", 1, "journal_meta", schema=journal.SCHEMA),
        ev(0.6, "worker", 1, "init_done", epoch=1, world_size=2),
        ev(1.0, "worker", 1, "commit", step=1, epoch=1),
        ev(5.0, "worker", 1, "commit", step=2, epoch=1),
        ev(9.0, "worker", 1, "commit", step=3, epoch=1),
        ev(10.0, "worker", 1, "fault_fired", point="elastic.step",
           action="crash", hit=4),
        ev(13.1, "worker", 1, "init_done", epoch=2, world_size=2),
        ev(13.9, "worker", 1, "sync_done", step=3, epoch=2),
        ev(14.6, "worker", 1, "commit", step=4, epoch=2),
    ])


class TestIncidentAnalyzer:
    def test_mttr_decomposition_synthetic(self, tmp_path):
        d = str(tmp_path / "synth")
        _write_synthetic(d)
        report = journal.incident_report(d)
        assert report["schema"] == journal.REPORT_SCHEMA
        assert report["summary"]["recoveries"] == 1
        (rec,) = report["recoveries"]
        assert rec["complete"] is True
        # cause attribution: rank, host, exit code, injected seam
        assert rec["cause"] == {
            "kind": "crash", "rank": 1, "host": "hostB",
            "exit_code": 43, "seam": "elastic.step:crash"}
        # phase decomposition against the synthetic timestamps
        # (t_fail = rank 1's last breath, the fault_fired at t=10)
        ph = rec["phases"]
        assert ph["detect"] == pytest.approx(0.5)
        assert ph["teardown"] == pytest.approx(1.5)
        assert ph["rendezvous"] == pytest.approx(0.25)
        assert ph["respawn"] == pytest.approx(0.25)
        assert ph["restore"] == pytest.approx(1.5)   # -> t=14.0
        assert ph["first_commit"] == pytest.approx(0.5)
        assert rec["mttr_s"] == pytest.approx(4.5)
        # step accounting: durable watermark 3, resumed 3, loss 0
        assert rec["steps"] == {"watermark": 3, "resumed": 3,
                                "committed_step_loss": 0}
        assert rec["postmortems"] == [
            {"rank": 1, "file": "postmortem-rank1.json",
             "reason": "crash", "step": 3}]
        assert rec["blacklisted"] == [
            {"host": "hostB", "window_s": 60.0, "failures": 1}]
        # epochs: 1 = start, 2 = recovery
        assert [(e["epoch"], e["kind"]) for e in report["epochs"]] \
            == [(1, "start"), (2, "recovery")]
        assert report["source"]["faults"] == [
            {"spec": "elastic.step:crash:at=4,rank=1", "seed": 7}]

    def test_merge_byte_determinism_golden(self, tmp_path):
        """Identical journal bytes -> identical report bytes, across
        repeated runs and an unrelated-cwd invocation."""
        d = str(tmp_path / "synth")
        _write_synthetic(d)
        p1, _ = journal.write_incident_report(
            d, out=str(tmp_path / "r1.json"))
        p2, _ = journal.write_incident_report(
            d, out=str(tmp_path / "r2.json"))
        b1 = open(p1, "rb").read()
        assert b1 == open(p2, "rb").read()
        # no environment-dependent content
        raw = b1.decode()
        assert str(tmp_path) not in raw
        assert "unix_time" not in raw

    def test_hung_worker_cause(self, tmp_path):
        """A liveness-detector kill is attributed as 'hung' with the
        stale heartbeat age, not as a crash with exit -9."""
        d = str(tmp_path / "hung")
        os.makedirs(d)

        def line(**kw):
            return json.dumps(kw, sort_keys=True) + "\n"

        with open(os.path.join(d, "journal-driver.jsonl"), "w") as f:
            f.write(line(t=1.0, n=0, role="driver", rank=-1,
                         type="epoch_published", epoch=1, size=1,
                         hosts={"0": "h"}))
            f.write(line(t=14.0, n=1, role="driver", rank=-1,
                         type="hung_worker", exit_rank=0, host="h",
                         age_s=4.0, timeout_s=4.0))
            f.write(line(t=14.1, n=2, role="driver", rank=-1,
                         type="detect", cause="hung", exit_rank=0,
                         host="h", code=-9, age_s=4.0, reset=1))
            f.write(line(t=14.2, n=3, role="driver", rank=-1,
                         type="gang_restart_begin", reset=1))
            f.write(line(t=15.0, n=4, role="driver", rank=-1,
                         type="teardown_done", reset=1))
            f.write(line(t=15.1, n=5, role="driver", rank=-1,
                         type="epoch_published", epoch=2, size=1,
                         hosts={"0": "h"}))
            f.write(line(t=15.2, n=6, role="driver", rank=-1,
                         type="respawn_done", epoch=2, ranks=1))
        with open(os.path.join(d, "journal-rank0.jsonl"), "w") as f:
            f.write(line(t=2.0, n=0, role="worker", rank=0,
                         type="commit", step=1, epoch=1,
                         durable=True))
            f.write(line(t=10.0, n=1, role="worker", rank=0,
                         type="fault_fired", point="elastic.step",
                         action="hang", hit=2))
            f.write(line(t=16.0, n=2, role="worker", rank=0,
                         type="sync_done", step=1, epoch=2))
            f.write(line(t=16.5, n=3, role="worker", rank=0,
                         type="commit", step=2, epoch=2,
                         durable=True))
        report = journal.incident_report(d)
        (rec,) = report["recoveries"]
        assert rec["cause"]["kind"] == "hung"
        assert rec["cause"]["heartbeat_stale_age_s"] == 4.0
        assert rec["cause"]["seam"] == "elastic.step:hang"
        # t_fail is the hang's firing; detect spans hang -> verdict
        assert rec["phases"]["detect"] == pytest.approx(4.1)
        assert rec["steps"]["committed_step_loss"] == 0

    def test_render_is_stringy(self, tmp_path):
        d = str(tmp_path / "synth")
        _write_synthetic(d)
        text = journal.render_incident_report(
            journal.incident_report(d))
        assert "crash on hostB" in text
        assert "teardown" in text and "first_commit" in text
        assert "watermark 3 -> resumed 3" in text


class TestCommittedArtifact:
    """The acceptance pin: the committed seeded-chaos artifact holds
    >= 2 recoveries (crash + hung) with complete decompositions and
    zero committed-step loss, and regenerates byte-identically from
    the committed journals."""

    def test_regenerates_byte_identically(self, tmp_path):
        out = str(tmp_path / "regen.json")
        journal.write_incident_report(ARTIFACT_DIR, out=out)
        assert open(out, "rb").read() == open(ARTIFACT, "rb").read()
        # the in-dir copy is the same bytes too
        assert open(os.path.join(
            ARTIFACT_DIR, "incident_report.json"), "rb").read() == \
            open(ARTIFACT, "rb").read()

    def test_acceptance_invariants(self):
        report = json.load(open(ARTIFACT))
        s = report["summary"]
        assert s["recoveries"] >= 2
        assert s["by_cause"].get("crash", 0) >= 1
        assert s["by_cause"].get("hung", 0) >= 1
        assert s["complete_decompositions"] == s["recoveries"]
        assert s["committed_step_loss_total"] == 0
        for rec in report["recoveries"]:
            for ph in ("detect", "teardown", "rendezvous", "respawn",
                       "restore", "first_commit"):
                assert rec["phases"][ph] is not None, (ph, rec)
            assert rec["cause"]["host"] and \
                rec["cause"]["rank"] is not None
            assert rec["cause"]["seam"] is not None
            assert rec["steps"]["committed_step_loss"] == 0
        # the fault schedule that produced it is carried in-band
        assert report["source"]["faults"][0]["seed"] == 11
        assert "elastic.step:crash" in \
            report["source"]["faults"][0]["spec"]


class TestEventSchemas:
    """The declared EVENT_SCHEMAS registry (hvdlint HVD008's source
    of truth): internal consistency, the strict-mode runtime
    companion, validation of every committed journal artifact, and
    the generated user_guide table's lockstep pin."""

    def test_registry_shape(self):
        names = [s.name for s in journal.EVENT_SCHEMAS]
        assert len(names) == len(set(names)), "duplicate event decls"
        for s in journal.EVENT_SCHEMAS:
            assert s.name and s.name == s.name.lower()
            assert s.writer and s.doc
            overlap = (set(s.required) | set(s.optional)) \
                & journal.BASE_FIELDS
            assert not overlap, (s.name, overlap)
            assert not set(s.required) & set(s.optional), s.name

    def test_critical_events_derived_from_registry(self):
        assert journal.CRITICAL_EVENTS <= journal.EVENT_NAMES
        assert journal.CRITICAL_EVENTS == {
            s.name for s in journal.EVENT_SCHEMAS if s.critical}
        # the load-bearing recovery edges stay critical
        for name in ("commit", "detect", "fault_fired",
                     "epoch_published", "first_commit"):
            assert name in journal.CRITICAL_EVENTS, name

    def test_schema_problems_round_trip(self):
        ok = journal.schema_problems(
            "commit", {"epoch": 2, "durable": True, "step": 7})
        assert ok == []
        assert any("undeclared event" in p for p in
                   journal.schema_problems("comitted", {"step": 1}))
        assert any("missing required" in p for p in
                   journal.schema_problems("commit", {"step": 1}))
        assert any("undeclared field" in p for p in
                   journal.schema_problems(
                       "commit", {"epoch": 1, "stepp": 7}))

    def test_strict_mode_warns_once_per_type(self, jdir,
                                             monkeypatch):
        monkeypatch.setenv("HOROVOD_JOURNAL_STRICT", "1")
        seen = []
        real = journal.hlog.warning
        monkeypatch.setattr(
            journal.hlog, "warning",
            lambda msg, *a: seen.append(msg % a if a else msg))
        j = journal.configure("worker", 0)
        j.event("fx_not_a_real_event", x=1)  # never raises
        j.event("fx_not_a_real_event", x=2)
        j.event("commit", epoch=1, durable=True, step=3)
        monkeypatch.setattr(journal.hlog, "warning", real)
        warns = [m for m in seen
                 if "HOROVOD_JOURNAL_STRICT" in m]
        assert len(warns) == 1  # deduped per event type
        assert "fx_not_a_real_event" in warns[0]
        # the record is still written — strict mode observes, never
        # drops
        lines = open(os.path.join(jdir,
                                  "journal-rank0.jsonl")).read()
        assert '"fx_not_a_real_event"' in lines

    def test_committed_artifacts_validate_against_registry(self):
        """r11 chaos, r14 preempt, r16 serving, r18 decode journals —
        every record of every committed artifact conforms to the
        registry UNCHANGED (the registry documents history, it does
        not rewrite it)."""
        import glob as _glob
        dirs = [os.path.join(REPO, "benchmarks", d) for d in
                ("incident_chaos_r11", "incident_preempt_r14",
                 "serving_trace_r16", "serving_decode_r18")]
        checked = 0
        problems = []
        for d in dirs:
            assert os.path.isdir(d), d
            for seg in sorted(_glob.glob(
                    os.path.join(d, "journal-*.jsonl*"))):
                for line in open(seg):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail: the loader's repair job
                    checked += 1
                    for p in journal.validate_event(rec):
                        problems.append((os.path.basename(seg), p))
        assert checked > 100  # the artifacts are substantial
        assert problems == [], problems[:10]

    def test_user_guide_table_is_generated_form(self):
        """The docs table between the hvdlint markers must be exactly
        event_schema_table_md()'s output — HVD008's drift leg assumes
        one source of truth."""
        guide = open(os.path.join(REPO, "docs",
                                  "user_guide.md")).read()
        begin = "<!-- hvdlint:event-schema-table:begin -->"
        end = "<!-- hvdlint:event-schema-table:end -->"
        assert begin in guide and end in guide
        between = guide.split(begin, 1)[1].split(end, 1)[0]
        assert between.strip("\n") == \
            journal.event_schema_table_md().strip("\n")


# -- live 2-rank chaos run (multiproc-gated like the other chaos
#    integration tests; the control-plane-only worker would run on
#    this jaxlib, but the probe keeps the gate uniform) --------------

_NO_MULTIPROC = ("this jaxlib's CPU backend cannot run cross-process "
                 "collectives (affects every multiprocess "
                 "integration test)")


@pytest.fixture(scope="module")
def multiproc_backend():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c",
         "import jax.numpy as jnp; import horovod_tpu as hvd; "
         "hvd.init(); hvd.allreduce(jnp.ones(4), name='probe'); "
         "hvd.shutdown()"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip(_NO_MULTIPROC)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


@pytest.mark.integration
def test_two_rank_chaos_names_injected_rank(tmp_path,
                                            multiproc_backend):
    """Live seeded soak (same shape as the committed artifact's):
    the incident report must attribute the crash to the rank the
    fault spec targeted, with a complete decomposition and zero
    committed-step loss."""
    jdir = tmp_path / "journal"
    jdir.mkdir()
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho localhost:2\n")
    script.chmod(0o755)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_LOG"] = str(tmp_path / "progress")
    env["ELASTIC_TEST_STEPS"] = "10"
    env["ELASTIC_TEST_SLEEP"] = "0.15"
    env["HOROVOD_JOURNAL_DIR"] = str(jdir)
    env["HOROVOD_FAULTS"] = (
        f"elastic.step:crash:at=3,rank=1,"
        f"once={tmp_path / 'crash.latch'}")
    env["HOROVOD_FAULTS_SEED"] = "7"
    env["HOROVOD_ELASTIC_TEARDOWN_GRACE"] = "3"
    p = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", str(script),
         "--min-num-proc", "2",
         "--host-change-detection-interval", "0.5",
         "--reset-limit", "3",
         sys.executable,
         os.path.join("tests", "journal_chaos_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out, _ = p.communicate(timeout=420)
    assert p.returncode == 0, out
    report = journal.incident_report(str(jdir))
    assert report["summary"]["recoveries"] >= 1
    rec = report["recoveries"][0]
    assert rec["cause"]["rank"] == 1, rec
    assert rec["cause"]["kind"] == "crash"
    assert rec["cause"]["seam"] == "elastic.step:crash"
    assert rec["complete"], rec
    assert rec["steps"]["committed_step_loss"] == 0
