"""Worker for the negotiated-controller integration tests: proves the
capability the reference exists for — ranks submitting collectives in
DIFFERENT orders still make progress with identical results (the
inline SPMD path would require identical program order).

Also exercises: hvd.join() with late/early ranks (join-aware Average),
and the clean-error path for cross-rank shape mismatches
(reference: test/parallel error-case tests, SURVEY.md §4 item 5)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    st = state()
    assert st.engine.controller is not None, \
        "negotiated controller must be on for size > 1"
    from horovod_tpu.core.native import NativeCore
    assert isinstance(st.engine.controller.core, NativeCore), \
        "multi-process control plane must be the native C++ core"

    # 1) OUT-OF-ORDER submission: rank 0 submits a,b,c; rank 1 c,b,a.
    names = ["ooo_a", "ooo_b", "ooo_c"]
    order = names if r == 0 else list(reversed(names))
    handles = {}
    for i, nm in enumerate(order):
        val = jnp.full((4,), float(ord(nm[-1])))
        handles[nm] = hvd.allreduce_async(val, name=nm, op=hvd.Sum)
    for nm in names:
        out = hvd.synchronize(handles[nm])
        np.testing.assert_allclose(
            np.asarray(out), np.full(4, n * float(ord(nm[-1]))))
    print(f"rank {r}: out-of-order OK")

    # 2) fusion: many small same-dtype tensors submitted together end
    # up agreed (and correct) regardless of arrival interleaving.
    hs = [hvd.allreduce_async(jnp.full((8,), float(i + r)), name=f"f{i}",
                              op=hvd.Sum)
          for i in range(16)]
    for i, h in enumerate(hs):
        expect = sum(float(i + rr) for rr in range(n))
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   np.full(8, expect))
    print(f"rank {r}: fused batch OK")

    # 3) shape mismatch -> clean error on every rank, no hang.
    try:
        bad = jnp.ones((2 + r,))
        hvd.allreduce(bad, name="mismatch", op=hvd.Sum)
        raise AssertionError("mismatch did not raise")
    except RuntimeError as e:
        assert "mismatch" in str(e).lower(), e
        print(f"rank {r}: mismatch error OK")

    # 4) join: rank 1 joins immediately; rank 0 keeps reducing.
    if r == 1:
        last = hvd.join()
    else:
        out = hvd.allreduce(jnp.full((3,), 10.0), name="after_join_1")
        # join-aware Average: only rank 0 contributes once others join.
        # (rank 1 may or may not have joined yet when this reduces; the
        # sum of contributions is 10 either way it is divided by the
        # active count at agreement, which rank 0 observes in the
        # result: 10/active. Both 10.0 (active=1) and 5.0 (active=2)
        # are consistent outcomes; assert it is one of them.)
        v = float(np.asarray(out)[0])
        assert v in (10.0, 5.0), v
        last = hvd.join()
    assert last in range(n), last
    print(f"rank {r}: join OK (last={last})")

    hvd.shutdown()
    print(f"rank {r}: NEGOTIATION ALL OK")


if __name__ == "__main__":
    main()
