"""Worker for the negotiated-controller integration tests: proves the
capability the reference exists for — ranks submitting collectives in
DIFFERENT orders still make progress with identical results (the
inline SPMD path would require identical program order).

Also exercises: hvd.join() with late/early ranks (join-aware Average),
and the clean-error path for cross-rank shape mismatches
(reference: test/parallel error-case tests, SURVEY.md §4 item 5)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    st = state()
    assert st.engine.controller is not None, \
        "negotiated controller must be on for size > 1"
    from horovod_tpu.core.native import NativeCore
    assert isinstance(st.engine.controller.core, NativeCore), \
        "multi-process control plane must be the native C++ core"

    # 1) OUT-OF-ORDER submission: rank 0 submits a,b,c; rank 1 c,b,a.
    names = ["ooo_a", "ooo_b", "ooo_c"]
    order = names if r == 0 else list(reversed(names))
    handles = {}
    for i, nm in enumerate(order):
        val = jnp.full((4,), float(ord(nm[-1])))
        handles[nm] = hvd.allreduce_async(val, name=nm, op=hvd.Sum)
    for nm in names:
        out = hvd.synchronize(handles[nm])
        np.testing.assert_allclose(
            np.asarray(out), np.full(4, n * float(ord(nm[-1]))))
    print(f"rank {r}: out-of-order OK")

    # 2) fusion: many small same-dtype tensors submitted together end
    # up agreed (and correct) regardless of arrival interleaving.
    hs = [hvd.allreduce_async(jnp.full((8,), float(i + r)), name=f"f{i}",
                              op=hvd.Sum)
          for i in range(16)]
    for i, h in enumerate(hs):
        expect = sum(float(i + rr) for rr in range(n))
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   np.full(8, expect))
    print(f"rank {r}: fused batch OK")

    # 3) shape mismatch -> clean error on every rank, no hang.
    try:
        bad = jnp.ones((2 + r,))
        hvd.allreduce(bad, name="mismatch", op=hvd.Sum)
        raise AssertionError("mismatch did not raise")
    except RuntimeError as e:
        assert "mismatch" in str(e).lower(), e
        print(f"rank {r}: mismatch error OK")

    # 3.5) response cache: steady-state re-announcements of known
    # (name, sig) pairs collapse to 5-byte ids (reference:
    # response_cache.cc bit-vector exchange). Observable as a sharp
    # drop in control bytes after the first round on ranks > 0.
    core = st.engine.controller.core
    names_c = [f"steady_{i:02d}_grad/layer{i}/kernel_momentum"
               for i in range(8)]

    def cache_round(tag):
        hs = [hvd.allreduce_async(jnp.full((4,), float(i + r)),
                                  name=nm, op=hvd.Sum)
              for i, nm in enumerate(names_c)]
        for i, h in enumerate(hs):
            expect = sum(float(i + rr) for rr in range(n))
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(h)), np.full(4, expect),
                err_msg=f"cache round {tag} name {i}")

    cb0 = core.control_bytes()
    cache_round("first")
    first_bytes = core.control_bytes() - cb0
    steady = []
    for k in range(4):
        a = core.control_bytes()
        cache_round(k)
        steady.append(core.control_bytes() - a)
    if r != 0:
        assert first_bytes > 0, "worker sent no control bytes?"
        avg = sum(steady) / len(steady)
        assert avg < 0.35 * first_bytes, (
            f"response cache ineffective: first={first_bytes}B "
            f"steady={steady}B")
    # sig change (new shape) must miss the cache and renegotiate
    # cleanly with correct results.
    out = hvd.allreduce(jnp.full((7,), 2.0), name=names_c[0],
                        op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), np.full(7, 2.0 * n))
    print(f"rank {r}: response cache OK "
          f"(first={first_bytes}B steady={steady})")

    # 3.6) timeline on rank 0: phases NEGOTIATE -> QUEUE -> DISPATCH
    # must appear as balanced lanes (reference: timeline.cc NEGOTIATE
    # phases — the round-1 verdict's dead hooks are now live).
    tl_path = None
    if r == 0:
        import tempfile
        tl_path = os.path.join(tempfile.gettempdir(),
                               f"hvd_tl_{os.getpid()}.json")
        hvd.start_timeline(tl_path, mark_cycles=True)
    hvd.barrier()
    for k in range(3):
        out = hvd.allreduce(jnp.full((4,), 1.0), name=f"tl_{k}")
        np.testing.assert_allclose(np.asarray(out), np.full(4, 1.0))
    hvd.barrier()
    if r == 0:
        import json
        hvd.stop_timeline()
        events = json.load(open(tl_path))
        os.unlink(tl_path)
        names = {e["name"] for e in events}
        assert {"NEGOTIATE", "QUEUE", "DISPATCH"} <= names, names
        assert any(e["name"].startswith("CYCLE") for e in events), \
            "mark_cycles produced no cycle markers"
        opens = {}
        for e in events:
            key = (e.get("tid"), e["name"])
            if e["ph"] == "B":
                opens[key] = opens.get(key, 0) + 1
            elif e["ph"] == "E":
                opens[key] = opens.get(key, 0) - 1
        assert all(v == 0 for v in opens.values()), opens
        # the coordinator-measured negotiate duration rides the wire
        assert any("coordinator_negotiate_us" in
                   str(e.get("args", {})) for e in events)
        print("rank 0: timeline phases OK")

    # 3.7) generic-op fusion: same-dtype/root broadcasts agreed
    # together execute as FUSED batches, one XLA launch each — not one
    # cycle per tensor (reference: controller.cc FuseResponses packs
    # non-allreduce responses too). exec_counts tracks
    # [batches, entries] per kind on the dispatch worker.
    ctl = st.engine.controller
    # Hold batch cuts until the ready set is stable for 3 cycles:
    # these phases assert FUSION, and on a loaded 1-core host an
    # unheld coordinator legitimately cuts single-entry batches
    # between slow submissions (observed flake). Restored to 0 after.
    ctl.core.set_quiescence(max(3, getattr(ctl.cfg,
                                           "batch_quiescence", 0)))
    bc0 = list(ctl.exec_counts.get("bc", [0, 0]))
    hs = [hvd.broadcast_async(
            jnp.full((4,), float(i) if r == 0 else -1.0),
            root_rank=0, name=f"bc_fuse_{i}") for i in range(8)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   np.full(4, float(i)))
    bc1 = ctl.exec_counts["bc"]
    bc_batches = bc1[0] - bc0[0]
    bc_entries = bc1[1] - bc0[1]
    assert bc_entries == 8, (bc0, bc1)
    assert bc_batches < bc_entries, (
        f"broadcasts never fused: {bc_batches} batches for "
        f"{bc_entries} entries")
    print(f"rank {r}: broadcast fusion OK "
          f"({bc_entries} entries in {bc_batches} batch(es))")

    # 3.8) fused UNEVEN allgathers: per-rank sizes ride the request
    # meta; same-dtype gathers agreed together land in one launch.
    ag0 = list(ctl.exec_counts.get("ag", [0, 0]))
    hs = [hvd.allgather_async(jnp.full((r + 1, 2), float(10 * i + r)),
                              name=f"ag_fuse_{i}") for i in range(6)]
    for i, h in enumerate(hs):
        expect = np.concatenate(
            [np.full((rr + 1, 2), float(10 * i + rr))
             for rr in range(n)])
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   expect)
    ag1 = ctl.exec_counts["ag"]
    ag_batches = ag1[0] - ag0[0]
    ag_entries = ag1[1] - ag0[1]
    assert ag_entries == 6, (ag0, ag1)
    assert ag_batches < ag_entries, (
        f"allgathers never fused: {ag_batches} batches for "
        f"{ag_entries} entries")
    print(f"rank {r}: allgather fusion OK "
          f"({ag_entries} entries in {ag_batches} batch(es))")

    # 3.9) fused reducescatters: same dtype/op submitted together
    # agree as batches and execute as ONE psum_scatter launch each
    # (rs|... fuse key; reference: FuseResponses packs same-type
    # reducescatter responses too). Mixed first dims fuse — the group
    # kernel tracks per-tensor row splits.
    rs0 = list(ctl.exec_counts.get("rs", [0, 0]))
    d0s = [n * 2, n * 2 + 1, n * 3, n * 2, n * 2 + 3, n * 2]
    # tensors built BEFORE the submit loop: the storm must be tight or
    # the coordinator legitimately cuts single-entry batches between
    # slow submissions (this asserts fusion, not pacing).
    vals = [jnp.arange(d0s[i] * 2, dtype=jnp.float32
                       ).reshape(d0s[i], 2) + float(r + i)
            for i in range(6)]
    hs = [hvd.reducescatter_async(vals[i], op=hvd.Sum,
                                  name=f"rs_fuse_{i}")
          for i in range(6)]
    for i, h in enumerate(hs):
        full = sum(np.arange(d0s[i] * 2, dtype=np.float32
                             ).reshape(d0s[i], 2) + float(rr + i)
                   for rr in range(n))
        base, rem = divmod(d0s[i], n)
        rows = [base + (1 if j < rem else 0) for j in range(n)]
        off = sum(rows[:r])
        np.testing.assert_allclose(
            np.asarray(hvd.synchronize(h)), full[off:off + rows[r]],
            rtol=1e-5)
    rs1 = ctl.exec_counts["rs"]
    rs_batches = rs1[0] - rs0[0]
    rs_entries = rs1[1] - rs0[1]
    assert rs_entries == 6, (rs0, rs1)
    assert rs_batches < rs_entries, (
        f"reducescatters never fused: {rs_batches} batches for "
        f"{rs_entries} entries")
    print(f"rank {r}: reducescatter fusion OK "
          f"({rs_entries} entries in {rs_batches} batch(es))")
    # restore the CONFIGURED value, not a hardcoded 0 — the process
    # may have been launched with HOROVOD_BATCH_QUIESCENCE set.
    ctl.core.set_quiescence(getattr(ctl.cfg, "batch_quiescence", 0))

    # 4) join: rank 1 joins immediately; rank 0 keeps reducing, then
    # proves a generic op agreed while a rank has joined gets a CLEAN
    # error (reference: join unsupported for non-allreduce ops) —
    # never a hang.
    if r == 1:
        last = hvd.join()
    else:
        out = hvd.allreduce(jnp.full((3,), 10.0), name="after_join_1")
        # join + COMPRESSION: rank 1 zero-fills this entry from the
        # negotiated sig alone. The sig carries the raw dtype, so the
        # joined rank lowers the identical fused program (fp32 zeros +
        # the same fp16 compress/decompress casts) the live rank does —
        # wire-dtype-only zero-fill made ranks jit DIFFERENT programs
        # around one collective (round-3 advisory, medium).
        outc = hvd.allreduce(jnp.full((5,), 6.0, jnp.float32),
                             name="after_join_fp16", op=hvd.Sum,
                             compression=hvd.Compression.fp16)
        # every rank but the joined rank 1 contributes 6.0
        np.testing.assert_allclose(np.asarray(outc),
                                   np.full(5, 6.0 * (n - 1)))
        assert outc.dtype == jnp.float32, outc.dtype
        print(f"rank {r}: join+compression zero-fill OK")
        # join-aware Average: only rank 0 contributes once others join.
        # (rank 1 may or may not have joined yet when this reduces; the
        # sum of contributions is 10 either way it is divided by the
        # active count at agreement, which rank 0 observes in the
        # result: 10/active. Both 10.0 (active=1) and 5.0 (active=2)
        # are consistent outcomes; assert it is one of them.)
        v = float(np.asarray(out)[0])
        assert v in (10.0, 5.0), v
        # Rank 1 will join without ever submitting this broadcast; the
        # coordinator must error it the moment it is agreed with
        # joined ranks present (not leave rank 0 blocked in a global
        # collective rank 1 never launches).
        try:
            hvd.broadcast(jnp.ones((2,)), root_rank=0,
                          name="join_bcast")
            raise AssertionError("broadcast after join did not error")
        except RuntimeError as e:
            assert "join" in str(e).lower(), e
            print(f"rank {r}: generic-op-after-join clean error OK")
        last = hvd.join()
    assert last in range(n), last
    print(f"rank {r}: join OK (last={last})")

    hvd.shutdown()
    print(f"rank {r}: NEGOTIATION ALL OK")


if __name__ == "__main__":
    main()
