"""Gradient compression (ops/compression.py) and the bucketing-layer
transform it feeds (parallel/train.py compression=..., the eager
DistributedGradientTransformation PowerSGD path): registry parsing,
the balanced matrix fold, cast round-trip bounds, PowerSGD round-trip
quality + full-rank exactness, warm-start determinism across fresh
interpreters (the SPMD purity contract), the error-feedback residual
surviving a simulated elastic restart via `JaxState`, bypass
exactness for ineligible leaves, and the HLO identity pins:
compression="none" lowers BYTE-IDENTICAL to the plain builder, and
powersgd genuinely changes the program. The 2-rank crash/restore leg
lives behind the same multiproc capability probe test_chaos.py uses
(tests/mp_worker_compression.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops import compression as C

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Registry / spec parsing
# ---------------------------------------------------------------------------

class TestRegistry:
    @pytest.mark.parametrize("raw,kind,rank", [
        ("none", "none", 4), ("fp16", "fp16", 4), ("bf16", "bf16", 4),
        ("powersgd", "powersgd", 4), ("powersgd:2", "powersgd", 2),
        ("powersgd(rank=8)", "powersgd", 8), ("POWERSGD:1",
                                              "powersgd", 1),
    ])
    def test_accepted_spellings(self, raw, kind, rank):
        spec = C.resolve_compression(raw)
        assert (spec.kind, spec.rank) == (kind, rank)

    def test_typo_raises_not_silently_uncompressed(self):
        with pytest.raises(ValueError, match="unknown"):
            C.resolve_compression("powersdg")
        with pytest.raises(ValueError, match="unparseable"):
            C.resolve_compression("powersgdx")
        with pytest.raises(ValueError, match="rank"):
            C.resolve_compression("powersgd:0")

    def test_knob_defaults_match_docs(self, monkeypatch):
        """The registry defaults the user guide's knob table states:
        none / rank 4 / warmup 0 / min_elements 4096."""
        for k in ("HOROVOD_COMPRESSION", "HOROVOD_COMPRESSION_RANK",
                  "HOROVOD_COMPRESSION_WARMUP_STEPS",
                  "HOROVOD_COMPRESSION_MIN_ELEMENTS"):
            monkeypatch.delenv(k, raising=False)
        spec = C.resolve_compression()
        assert spec == C.CompressionSpec("none", 4, 4096, 0)

    def test_tags(self):
        assert C.resolve_compression("powersgd:4").tag() == "powersgd:4"
        assert C.resolve_compression("bf16").tag() == "bf16"
        assert C.tag_of(C.Compression.none) == "none"
        assert C.tag_of(C.Compression.fp16) == "fp16"
        assert C.tag_of(C.Compression.powersgd(rank=2)) == "powersgd:2"

    def test_spec_of_every_eager_value(self):
        assert C.spec_of(C.Compression.bf16).kind == "bf16"
        assert C.spec_of("powersgd:3").rank == 3
        assert C.spec_of(C.Compression.powersgd(rank=5)).rank == 5
        s = C.CompressionSpec("fp16", 1, 2, 3)
        assert C.spec_of(s) is s
        with pytest.raises(ValueError):
            C.spec_of(object())


# ---------------------------------------------------------------------------
# Matrix fold + eligibility
# ---------------------------------------------------------------------------

class TestMatrixFold:
    def test_2d_is_identity(self):
        assert C.matrix_shape((128, 256)) == (128, 256)
        assert C.matrix_shape((3, 1024)) == (3, 1024)

    def test_scan_stacked_block_folds_balanced(self):
        """The load-bearing case: a scan-stacked transformer block
        must NOT fold to (layers, d*d) — rank-r across layers with
        factors a third the raw bytes — but to the balanced
        (layers*d, d) view."""
        assert C.matrix_shape((24, 1024, 1024)) == (24 * 1024, 1024)
        assert C.matrix_shape((2, 64, 64)) == (2 * 64, 64)

    def test_fold_is_axis_boundary_only(self):
        # (4, 4, 4): boundaries give (4,16) and (16,4); the first
        # minimizer wins deterministically.
        assert C.matrix_shape((4, 4, 4)) == (4, 16)

    def test_wire_elements_track_fold(self):
        p, q = C.powersgd_wire_elements((24, 1024, 1024), 4)
        assert (p, q) == (24 * 1024 * 4, 1024 * 4)
        # and the factor wire actually beats raw by a lot
        raw = 24 * 1024 * 1024
        assert raw / (p + q) > 100

    def test_effective_rank_caps_at_both_dims(self):
        assert C.effective_rank((2, 4096), 4) == 2
        assert C.effective_rank((512, 512), 4) == 4
        assert C.effective_rank((64, 3), 8) == 3

    def test_eligibility(self):
        assert C.powersgd_eligible((64, 64), jnp.float32, 1024)
        assert not C.powersgd_eligible((4096,), jnp.float32, 1024)
        assert not C.powersgd_eligible((64, 64), jnp.int32, 1024)
        assert not C.powersgd_eligible((16, 16), jnp.float32, 1024)
        # degenerate matrix view: (1, n) compresses nothing
        assert not C.powersgd_eligible((1, 4096), jnp.float32, 1024)


# ---------------------------------------------------------------------------
# Cast compressors: round-trip bounds
# ---------------------------------------------------------------------------

class TestCastRoundTrip:
    @pytest.mark.parametrize("comp,wire,rtol", [
        (C.Compression.fp16, jnp.float16, 1e-3),
        (C.Compression.bf16, jnp.bfloat16, 8e-3),
    ])
    def test_round_trip_relative_error(self, comp, wire, rtol):
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(256,)), jnp.float32)
        c, ctx = comp.compress(x)
        assert c.dtype == wire and ctx == jnp.float32
        back = comp.decompress(c, ctx)
        assert back.dtype == jnp.float32
        assert float(jnp.max(jnp.abs(back - x)
                             / (jnp.abs(x) + 1e-12))) < rtol

    def test_integer_leaves_pass_through(self):
        x = jnp.arange(8, dtype=jnp.int32)
        c, ctx = C.Compression.fp16.compress(x)
        assert c.dtype == jnp.int32 and ctx is None
        assert (C.Compression.fp16.decompress(c, ctx) == x).all()

    def test_bf16_survives_fp16_overflow_range(self):
        """The TPU-native wire choice: 1e5 overflows fp16 to inf but
        bf16 keeps the exponent (the no-overflow-cliff rationale)."""
        x = jnp.asarray([1e5], jnp.float32)
        cf, _ = C.Compression.fp16.compress(x)
        cb, _ = C.Compression.bf16.compress(x)
        assert bool(jnp.isinf(cf.astype(jnp.float32))[0])
        assert float(cb.astype(jnp.float32)[0]) == pytest.approx(
            1e5, rel=0.01)


# ---------------------------------------------------------------------------
# PowerSGD math
# ---------------------------------------------------------------------------

class TestPowerSGDMath:
    def test_gram_orthogonalize_columns_orthonormal(self):
        p = jnp.asarray(np.random.default_rng(1).normal(
            size=(64, 4)), jnp.float32)
        q = C.gram_orthogonalize(p)
        gram = np.asarray(q.T @ q, np.float64)
        assert np.allclose(gram, np.eye(4), atol=1e-4)

    def test_gram_orthogonalize_zero_matrix_no_nans(self):
        """First-step all-zero cotangents: the jitter keeps Cholesky
        positive-definite — scaled basis out, never NaNs."""
        q = C.gram_orthogonalize(jnp.zeros((16, 2), jnp.float32))
        assert bool(jnp.isfinite(q).all())

    def test_full_rank_round_trip_is_exact(self):
        """rank >= min(n, m) reproduces the exact sum: PowerSGD's
        error is purely the rank deficit."""
        rng = np.random.default_rng(2)
        m = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)
        q0 = C.init_q((8, 6), 6, 0)
        outs, _, es = C.powersgd_reduce(
            [m], [q0], [jnp.zeros((8, 6), jnp.float32)],
            lambda x: x, 1)
        assert np.allclose(np.asarray(outs[0]), np.asarray(m),
                           atol=1e-4)
        assert float(jnp.abs(es[0]).max()) < 1e-4

    def test_error_feedback_returns_the_residual(self):
        """The EF telescoping identity: out_t = m + e_{t-1} - e_t, so
        after T rounds on the SAME gradient the cumulative
        communicated signal is exactly T*m - e_T. With the residual
        bounded (it is — the feedback loop has a fixed point), the
        RELATIVE error of what crossed the wire shrinks with T:
        compression error is deferred, never lost. The target is
        what PowerSGD is built for — a low-rank-dominant gradient
        (rank-1 signal + small dense noise); on a full-rank Gaussian
        rank-r tracking has nothing to grab and the residual grows
        for many steps (that regime is the min_elements/rank
        knob's problem, not EF's)."""
        rng = np.random.default_rng(3)
        m = jnp.asarray(
            rng.normal(size=(32, 1)) @ rng.normal(size=(1, 16))
            + 0.05 * rng.normal(size=(32, 16)), jnp.float32)
        qs = [C.init_q((32, 16), 2, 0)]
        es = [jnp.zeros((32, 16), jnp.float32)]
        total = jnp.zeros_like(m)
        norms, rels = [], []
        m_norm = float(jnp.linalg.norm(m))
        for t in range(1, 11):
            outs, qs, es = C.powersgd_reduce([m], qs, es,
                                             lambda x: x, 1)
            total = total + outs[0]
            # telescoping: cumulative error IS the current residual
            assert np.allclose(np.asarray(t * m - total),
                               np.asarray(es[0]), atol=1e-3)
            norms.append(float(jnp.linalg.norm(es[0])))
            rels.append(norms[-1] / (t * m_norm))
        # residual stays small vs the signal => the relative wire
        # error decreases (measured: 0.040 -> 0.027 over 10 rounds)
        assert max(norms) < m_norm
        assert rels[-1] < 0.75 * rels[0]

    def test_multi_leaf_packing_matches_single(self):
        """Two leaves through one packed wire == each alone: the
        pack/slice bookkeeping is transparent."""
        rng = np.random.default_rng(4)
        a = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        b = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        qa, qb = C.init_q((16, 8), 2, 0), C.init_q((8, 8), 2, 1)
        za = jnp.zeros_like(a)
        zb = jnp.zeros_like(b)
        packed, _, _ = C.powersgd_reduce([a, b], [qa, qb], [za, zb],
                                         lambda x: x, 1)
        solo_a, _, _ = C.powersgd_reduce([a], [qa], [za],
                                         lambda x: x, 1)
        solo_b, _, _ = C.powersgd_reduce([b], [qb], [zb],
                                         lambda x: x, 1)
        assert np.allclose(np.asarray(packed[0]),
                           np.asarray(solo_a[0]), atol=1e-5)
        assert np.allclose(np.asarray(packed[1]),
                           np.asarray(solo_b[0]), atol=1e-5)

    def test_init_q_deterministic_across_interpreters(self):
        """A fresh interpreter derives bit-identical warm-start
        factors — the cross-process SPMD purity contract (every rank
        computes Q locally; divergent factors would compress
        different subspaces on different ranks)."""
        code = (
            "import numpy as np\n"
            "from horovod_tpu.ops.compression import init_q\n"
            "q = np.asarray(init_q((24, 64, 64), 4, 7), np.float32)\n"
            "print(q.tobytes().hex())\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)
        outs = {subprocess.run(
            [sys.executable, "-c", code], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=120,
            check=True).stdout.strip() for _ in range(2)}
        assert len(outs) == 1
        here = np.asarray(C.init_q((24, 64, 64), 4, 7),
                          np.float32).tobytes().hex()
        assert outs == {here}


# ---------------------------------------------------------------------------
# The jit plane: build_train_step(compression=...)
# ---------------------------------------------------------------------------

def _mesh():
    return Mesh(np.array(jax.devices()[:8]), axis_names=("proc",))


def _loss(params, batch):
    h = jnp.tanh(batch[:, None] * params["w1"][None, :])
    return jnp.mean((h @ params["w2"]) ** 2) + jnp.mean(params["b"] ** 2)


def _params():
    # w2 (32x16 f32, 512 elements) is the one powersgd-eligible leaf
    # at min_elements=256; w1/b bypass (1-D / too small).
    return {"w1": jnp.arange(32.0) / 32.0,
            "w2": jnp.ones((32, 16)) * 0.1 + jnp.arange(
                32.0 * 16).reshape(32, 16) * 1e-3,
            "b": jnp.zeros(3)}


def _batch(mesh):
    return jax.device_put(jnp.arange(8.0),
                          NamedSharding(mesh, P("proc")))


class TestJitPlane:
    def test_none_is_byte_identical_hlo(self, monkeypatch):
        """compression="none" (explicit AND knob-default) lowers the
        IDENTICAL program to a build that never heard of compression
        — the transform is free when off. powersgd must genuinely
        change the program, or the knob is theater."""
        from horovod_tpu.parallel.train import build_train_step
        for k in ("HOROVOD_COMPRESSION", "HOROVOD_NUMERICS_GUARD"):
            monkeypatch.delenv(k, raising=False)
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = _batch(mesh)
        base = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512)
        expl = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512,
                                compression="none")
        hlo_base = base.lower(params, st, batch).as_text()
        assert expl.lower(params, st, batch).as_text() == hlo_base
        monkeypatch.setenv("HOROVOD_COMPRESSION", "none")
        knob = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512)
        assert knob.lower(params, st, batch).as_text() == hlo_base
        monkeypatch.setenv("HOROVOD_COMPRESSION", "bf16")
        cast = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512)
        assert cast.lower(params, st, batch).as_text() != hlo_base

    def test_powersgd_bypass_leaves_stay_exact(self, monkeypatch):
        """Under powersgd only eligible leaves go lossy: w1 and b
        (bypass family) update bit-identically to the uncompressed
        step, while w2 (the compressed leaf) differs — the bypass is
        real, per-leaf, and doesn't leak."""
        from horovod_tpu.parallel.train import (build_train_step,
                                                init_compression_state)
        monkeypatch.delenv("HOROVOD_COMPRESSION", raising=False)
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = _batch(mesh)
        exact = build_train_step(_loss, opt, mesh, donate=False,
                                 overlap=True, overlap_threshold=512)
        p_e, _, _ = exact(params, st, batch)
        comp = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512,
                                compression="powersgd:2",
                                compression_min_elements=256)
        cstate, _ = init_compression_state(
            params, mesh, compression="powersgd:2",
            compression_min_elements=256)
        assert set(cstate["q"]) == set(cstate["e"])
        assert len(cstate["q"]) == 1  # exactly w2
        p_c, _, _, _ = comp(params, st, batch, cstate)
        np.testing.assert_array_equal(np.asarray(p_e["w1"]),
                                      np.asarray(p_c["w1"]))
        np.testing.assert_array_equal(np.asarray(p_e["b"]),
                                      np.asarray(p_c["b"]))
        assert not np.allclose(np.asarray(p_e["w2"]),
                               np.asarray(p_c["w2"]), atol=1e-9)

    def test_everything_ineligible_matches_exact(self, monkeypatch):
        """min_elements above every leaf: the powersgd build must
        reduce to the exact path for the whole tree (all-bypass), and
        the state is empty."""
        from horovod_tpu.parallel.train import (build_train_step,
                                                init_compression_state,
                                                plan_overlap)
        monkeypatch.delenv("HOROVOD_COMPRESSION", raising=False)
        mesh = _mesh()
        opt = optax.sgd(0.1)
        params = _params()
        st = opt.init(params)
        batch = _batch(mesh)
        plan = plan_overlap(params, mesh, overlap_threshold=512,
                            compression="powersgd",
                            compression_min_elements=1 << 20)
        assert set(plan.bucket_compression) == {"none"}
        cstate, _ = init_compression_state(
            params, mesh, compression="powersgd",
            compression_min_elements=1 << 20)
        assert cstate == {"q": {}, "e": {}}
        exact = build_train_step(_loss, opt, mesh, donate=False,
                                 overlap=True, overlap_threshold=512)
        comp = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512,
                                compression="powersgd",
                                compression_min_elements=1 << 20)
        p_e, _, _ = exact(params, st, batch)
        p_c, _, _, _ = comp(params, st, batch, cstate)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p_e[k]),
                                          np.asarray(p_c[k]))

    def test_residual_survives_simulated_elastic_restart(self,
                                                         monkeypatch):
        """The first-class compression_state through `JaxState`:
        3 steps -> commit -> 2 more steps must equal 3 steps ->
        commit -> CRASH (state clobbered) -> restore -> 2 more steps,
        bit-for-bit. A restart that silently reset the residual would
        diverge immediately — accumulated error is gradient signal."""
        from horovod_tpu.elastic.state import JaxState
        from horovod_tpu.parallel.train import (build_train_step,
                                                init_compression_state)
        monkeypatch.delenv("HOROVOD_COMPRESSION", raising=False)
        mesh = _mesh()
        opt = optax.adam(1e-2)
        params = _params()
        batch = _batch(mesh)
        step = build_train_step(_loss, opt, mesh, donate=False,
                                overlap=True, overlap_threshold=512,
                                compression="powersgd:2",
                                compression_min_elements=256)

        def run(p, s, c, n):
            for _ in range(n):
                p, s, _, c = step(p, s, batch, c)
            return p, s, c

        cstate0, _ = init_compression_state(
            params, mesh, compression="powersgd:2",
            compression_min_elements=256)
        p3, s3, c3 = run(params, opt.init(params), cstate0, 3)
        (e_key,) = c3["e"]
        assert float(jnp.abs(c3["e"][e_key]).max()) > 0  # EF is live

        state = JaxState(params=p3, opt_state=s3,
                         compression_state=c3, step=3)
        state.save()  # the commit
        # the crash: everything in device memory is lost/garbage
        state.params = jax.tree.map(jnp.zeros_like, p3)
        state.opt_state = jax.tree.map(jnp.zeros_like, s3)
        state.compression_state = jax.tree.map(jnp.zeros_like, c3)
        state.restore()
        p_r, _, _ = run(state.params, state.opt_state,
                        state.compression_state, 2)
        p_u, _, _ = run(p3, s3, c3, 2)  # uninterrupted
        for k in params:
            np.testing.assert_array_equal(np.asarray(p_u[k]),
                                          np.asarray(p_r[k]))


# ---------------------------------------------------------------------------
# 2-rank crash/restore chaos leg (real subprocesses)
# ---------------------------------------------------------------------------

_NO_MULTIPROC = ("this jaxlib's CPU backend cannot run cross-process "
                 "collectives (affects every multiprocess "
                 "integration test)")


@pytest.fixture(scope="module")
def multiproc_backend():
    """Same cheap capability probe as test_chaos.py: one tiny 2-rank
    allreduce before burning restarts on an incapable backend."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, "-c",
         "import jax.numpy as jnp; import horovod_tpu as hvd; "
         "hvd.init(); hvd.allreduce(jnp.ones(4), name='probe'); "
         "hvd.shutdown()"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    if "Multiprocess computations aren't implemented" in (
            r.stdout + r.stderr):
        pytest.skip(_NO_MULTIPROC)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


@pytest.mark.integration
def test_two_rank_powersgd_crash_restore(tmp_path, multiproc_backend):
    """Eager-plane PowerSGD across two REAL processes: phase `ref`
    trains 6 uninterrupted steps; phase `a` trains 3, commits, and
    hard-exits; phase `b` restores the commit (PowerSGD Q/residual
    ride inside opt_state, exactly what elastic JaxState snapshots)
    and finishes — the resumed loss must match the uninterrupted run
    to float tolerance, proving the error memory crossed the crash."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["COMPRESSION_WORKER_DIR"] = str(tmp_path)

    def run(phase, check=True):
        e = dict(env, COMPRESSION_WORKER_PHASE=phase)
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable,
             os.path.join(REPO, "tests", "mp_worker_compression.py")],
            cwd=REPO, env=e, capture_output=True, text=True,
            timeout=300)
        if check:
            assert r.returncode == 0, r.stdout + "\n" + r.stderr
        return r

    run("ref")
    ra = run("a", check=False)
    assert ra.returncode != 0, "phase a is supposed to crash"
    assert "COMPRESSION WORKER COMMITTED" in ra.stdout, (
        ra.stdout + "\n" + ra.stderr)
    run("b")
    import json
    ref = json.loads((tmp_path / "ref.json").read_text())
    res = json.loads((tmp_path / "resumed.json").read_text())
    assert res["loss"] == pytest.approx(ref["loss"], abs=1e-5), (
        ref, res)
    assert res["residual_norm"] == pytest.approx(
        ref["residual_norm"], abs=1e-4)
    assert ref["residual_norm"] > 0  # EF engaged in both runs
