"""Slice-atomic elastic membership tests: discovery slice-column
parsing, the -H @slice suffix, SliceTracker rump parking / forget
window, driver-level whole-slice admission + blacklist escalation +
contiguous-rank invariants, the host.preempt SIGTERM->SIGKILL seam,
the committed preemption-storm artifact's regeneration pin, and
(nightly) the live whole-slice preemption-storm soak behind
benchmarks/INCIDENT_preempt_r14.json."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu import faults, journal  # noqa: E402
from horovod_tpu.runner.elastic import driver as driver_mod  # noqa: E402
from horovod_tpu.runner.elastic.discovery import (  # noqa: E402
    HostDiscovery, HostDiscoveryScript, hosts_key,
    parse_discovery_line)
from horovod_tpu.runner.elastic.driver import (  # noqa: E402
    ElasticDriver, _Slot)
from horovod_tpu.runner.elastic.slices import SliceTracker  # noqa: E402
from horovod_tpu.runner.hosts import (  # noqa: E402
    HostSlots, RankInfo, assign_ranks, parse_hosts, per_chip_env)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT_DIR = os.path.join(REPO, "benchmarks", "incident_preempt_r14")
ARTIFACT = os.path.join(REPO, "benchmarks", "INCIDENT_preempt_r14.json")


# -- discovery parsing ----------------------------------------------

class TestDiscoveryParsing:
    def test_plain_lines_keep_legacy_contract(self):
        assert parse_discovery_line("h1:4") == HostSlots("h1", 4)
        assert parse_discovery_line("h1") == HostSlots("h1", 1)
        assert parse_discovery_line("h1").slice_id is None

    def test_slice_column(self):
        h = parse_discovery_line("h1:4 slice=pod0")
        assert h == HostSlots("h1", 4, "pod0")
        assert parse_discovery_line("h2 slice=pod1").slots == 1

    def test_unknown_attribute_fails_loud(self):
        with pytest.raises(ValueError):
            parse_discovery_line("h1:4 zone=us-central1")
        with pytest.raises(ValueError):
            parse_discovery_line("h1:4 slice")

    def test_empty_slice_id_rejected(self):
        with pytest.raises(ValueError):
            parse_discovery_line("h1:4 slice=")

    def test_hosts_key_shapes(self):
        # slice-less lists keep the historical {host: slots} shape so
        # single-slice jobs' membership-change detection is unchanged
        plain = [HostSlots("h1", 4), HostSlots("h2", 4)]
        assert hosts_key(plain) == {"h1": 4, "h2": 4}
        mixed = [HostSlots("h1", 4, "pod0"), HostSlots("h2", 4)]
        key = hosts_key(mixed)
        assert key["h1"] == (4, "pod0") and key["h2"] == 4

    def test_script_end_to_end(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\n"
                          "echo 'h1:4 slice=pod0'\n"
                          "echo 'h2:4 slice=pod0'\n"
                          "echo h3:2\n")
        script.chmod(0o755)
        hosts = HostDiscoveryScript(
            str(script)).find_available_hosts_and_slots()
        assert hosts == [HostSlots("h1", 4, "pod0"),
                         HostSlots("h2", 4, "pod0"),
                         HostSlots("h3", 2)]


class TestParseHostsSlices:
    def test_at_suffix(self):
        hosts = parse_hosts("h1:4@pod0,h2:4@pod0,h3:2@pod1", 10)
        assert [h.slice_id for h in hosts] == ["pod0", "pod0", "pod1"]

    def test_empty_slice_suffix_rejected(self):
        with pytest.raises(ValueError):
            parse_hosts("h1:4@", 4)

    def test_rank_env_legacy_without_slice(self):
        infos = assign_ranks([HostSlots("h1", 2)], 2)
        env = infos[1].env()
        assert set(env) == {
            "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
            "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK",
            "HOROVOD_CROSS_SIZE"}

    def test_rank_env_carries_slice_id(self):
        infos = assign_ranks([HostSlots("h1", 2, "pod0")], 2)
        assert infos[0].env()["HOROVOD_ELASTIC_SLICE_ID"] == "pod0"

    def test_slice_ranks_contiguous(self):
        hosts = parse_hosts("h1:4@pod0,h2:4@pod0,h3:4@pod1,h4:4@pod1",
                            16)
        infos = assign_ranks(hosts, 16)
        by_slice = {}
        for i in infos:
            by_slice.setdefault(i.slice_id, []).append(i.rank)
        for sid, ranks in by_slice.items():
            assert ranks == list(range(min(ranks), max(ranks) + 1)), \
                (sid, ranks)

    def test_per_chip_single_implicit_slice_unchanged(self):
        infos = assign_ranks([HostSlots("h1", 2), HostSlots("h2", 2)],
                             4)
        env = per_chip_env(infos[2], infos)
        # the whole job is one mesh: every slot in the address list,
        # task id == rank, exactly as before slices existed
        assert env["TPU_PROCESS_ADDRESSES"] == \
            "h1:8476,h1:8477,h2:8476,h2:8477"
        assert env["CLOUD_TPU_TASK_ID"] == "2"

    def test_per_chip_mesh_is_per_slice(self):
        hosts = [HostSlots("h1", 2, "pod0"), HostSlots("h2", 2, "pod1")]
        infos = assign_ranks(hosts, 4)
        env = per_chip_env(infos[2], infos)  # rank 2 = h2 slot 0
        assert env["TPU_PROCESS_ADDRESSES"] == "h2:8476,h2:8477"
        # slice-relative task id: pod1's first process is task 0
        assert env["CLOUD_TPU_TASK_ID"] == "0"


# -- SliceTracker ---------------------------------------------------

P0 = [HostSlots("a1", 2, "p0"), HostSlots("a2", 2, "p0")]
P1 = [HostSlots("b1", 2, "p1"), HostSlots("b2", 2, "p1")]


class TestSliceTracker:
    def test_rump_parked_until_complete(self):
        t = SliceTracker()
        t.observe(P0)
        admitted, rumps, newly = t.admit(P0[:1], now=0.0)
        assert admitted == [] and rumps == P0[:1] and newly == set()
        admitted, rumps, newly = t.admit(P0, now=1.0)
        assert admitted == P0 and rumps == [] and newly == {"p0"}

    def test_sliceless_always_admitted(self):
        t = SliceTracker()
        plain = [HostSlots("h1", 4)]
        t.observe(plain)
        admitted, rumps, _ = t.admit(plain, now=0.0)
        assert admitted == plain and rumps == []

    def test_slice_major_input_order(self):
        t = SliceTracker()
        interleaved = [P0[0], P1[0], P0[1], P1[1]]
        t.observe(interleaved)
        admitted, _, _ = t.admit(interleaved, now=0.0)
        assert [h.slice_id for h in admitted] == \
            ["p0", "p0", "p1", "p1"]
        assert [h.host for h in admitted] == ["a1", "a2", "b1", "b2"]

    def test_forget_window_rebaselines(self):
        t = SliceTracker(forget_seconds=5.0)
        t.observe(P0)
        admitted, rumps, _ = t.admit(P0[:1], now=100.0)
        assert admitted == [] and rumps == P0[:1]
        # still inside the window: parked
        admitted, _, _ = t.admit(P0[:1], now=104.0)
        assert admitted == []
        # past the window: reconfiguration, not outage
        admitted, _, newly = t.admit(P0[:1], now=105.5)
        assert admitted == P0[:1] and newly == {"p0"}
        assert t.members("p0") == {"a1"}

    def test_rehomed_host_leaves_old_slice(self):
        t = SliceTracker()
        t.observe(P0)
        moved = [P0[0], HostSlots("a2", 2, "p9")]
        t.observe(moved)
        assert t.members("p0") == {"a1"}
        assert t.slice_of("a2") == "p9"
        admitted, rumps, _ = t.admit(moved, now=0.0)
        assert admitted == moved and rumps == []

    def test_atomic_off_admits_rumps(self):
        t = SliceTracker(atomic=False)
        t.observe(P0)
        admitted, rumps, _ = t.admit(P0[:1], now=0.0)
        assert admitted == P0[:1] and rumps == []


# -- driver-level membership ----------------------------------------

class ListDiscovery(HostDiscovery):
    """In-memory discovery: tests mutate .hosts between polls."""

    def __init__(self, hosts):
        self.hosts = list(hosts)

    def find_available_hosts_and_slots(self):
        return list(self.hosts)


@pytest.fixture
def mkdriver():
    """ElasticDriver factory; rendezvous servers stopped and journal
    module state restored after the test."""
    made = []

    def make(hosts, **kw):
        disc = ListDiscovery(hosts)
        kw.setdefault("env", {})
        d = ElasticDriver([sys.executable, "-c", "pass"], disc, **kw)
        made.append(d)
        return d, disc

    yield make
    for d in made:
        d.rendezvous.stop()
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None
    journal._first_commit_pending = None


POD0 = [HostSlots(f"h{i}", 1, "pod0") for i in range(4)]
POD1 = [HostSlots("x1", 1, "pod1"), HostSlots("x2", 1, "pod1")]


class TestDriverMembership:
    def test_rump_slice_is_never_assigned_ranks(self, mkdriver):
        """Acceptance pin: a 3-of-4-host slice must not hold ranks."""
        drv, disc = mkdriver(POD0 + POD1)
        drv._discover()  # learn full membership
        disc.hosts = [h for h in POD0 if h.host != "h3"] + POD1
        admitted = drv._discover()
        assert all(h.slice_id == "pod1" for h in admitted)
        infos, table = drv._assignments(admitted)
        assert sorted(i.host for i in infos) == ["x1", "x2"]
        assert all(i.slice_id == "pod1" for i in infos)
        assert not any(k[0].startswith("h") for k in table)

    def test_whole_slice_blacklist_on_member_failure(self, mkdriver):
        drv, _ = mkdriver(POD0 + POD1)
        drv._discover()
        drv._blacklist_failed({"h0": "crash"})
        now = time.time()
        assert set(drv.blacklist) == {"h0", "h1", "h2", "h3"}
        for until in drv.blacklist.values():
            assert 0 < until - now <= drv.blacklist_window + 1

    def test_escalation_window_keyed_by_slice(self, mkdriver):
        """The window doubles even when a DIFFERENT member fails the
        second time: the slice, not the host, is the flapping unit."""
        drv, _ = mkdriver(POD0 + POD1)
        drv._discover()
        drv._blacklist_failed({"h0": "crash"})
        drv.blacklist = {}  # simulate window expiry
        drv._blacklist_failed({"h2": "hung"})
        now = time.time()
        for until in drv.blacklist.values():
            assert until - now > drv.blacklist_window * 1.5
        assert drv._slice_failures["pod0"] == 2

    def test_min_np_guard_refuses_slice_eviction(self, mkdriver):
        drv, _ = mkdriver(list(POD0), min_np=3)
        drv._discover()
        drv._blacklist_failed({"h1": "crash"})
        assert drv.blacklist == {}

    def test_contiguous_ranks_from_interleaved_discovery(self,
                                                         mkdriver):
        interleaved = [POD0[0], POD1[0], POD0[1], POD1[1]]
        drv, _ = mkdriver(interleaved)
        admitted = drv._discover()
        infos, _ = drv._assignments(admitted)
        by_slice = {}
        for i in infos:
            by_slice.setdefault(i.slice_id, []).append(i.rank)
        assert by_slice == {"pod0": [0, 1], "pod1": [2, 3]}

    def test_max_np_admits_whole_slices_only(self, mkdriver):
        pods = [HostSlots("a1", 2, "p0"), HostSlots("b1", 2, "p1")]
        drv, _ = mkdriver(pods, max_np=3)
        admitted = drv._discover()
        assert [h.slice_id for h in admitted] == ["p0"]
        # slice-less lists keep the legacy truncate-at-np behavior
        plain = [HostSlots("h1", 2), HostSlots("h2", 2)]
        drv2, _ = mkdriver(plain, max_np=3)
        assert drv2._discover() == plain

    def test_single_slice_epoch_table_unchanged(self, mkdriver,
                                                monkeypatch):
        """Acceptance pin: a slice-less job's published assignment
        table is byte-for-byte the pre-slice contract — exactly the
        legacy key set, no slice variable anywhere."""
        ports = iter([43211, 43212])
        monkeypatch.setattr(driver_mod, "free_port",
                            lambda: next(ports))
        drv, _ = mkdriver([HostSlots("localhost", 2)], min_np=2)
        hosts = drv._discover()
        infos, table = drv._publish_epoch(hosts)
        rdv = f"localhost:{drv.rendezvous.port}"
        expected = {}
        for lr in (0, 1):
            expected[("localhost", lr)] = {
                "HOROVOD_RANK": str(lr),
                "HOROVOD_SIZE": "2",
                "HOROVOD_LOCAL_RANK": str(lr),
                "HOROVOD_LOCAL_SIZE": "2",
                "HOROVOD_CROSS_RANK": "0",
                "HOROVOD_CROSS_SIZE": "1",
                "HOROVOD_COORDINATOR_ADDR": "localhost:43211",
                "HOROVOD_CONTROL_ADDR": "localhost:43212",
                "HOROVOD_CONTROL_HOSTS": "localhost,localhost",
                "HOROVOD_HOSTNAME": "localhost",
                "HOROVOD_RENDEZVOUS_ADDR": rdv,
                "HOROVOD_ELASTIC_EPOCH": "1",
            }
        assert table == expected

    def test_journal_slice_events(self, mkdriver, tmp_path):
        jdir = str(tmp_path / "journal")
        drv, _ = mkdriver(POD0 + POD1,
                          env={"HOROVOD_JOURNAL_DIR": jdir})
        drv._discover()
        drv._blacklist_failed({"h0": "preempt"})
        journal._journal.close()
        journal._journal = None
        events, _ = journal.read_journal(
            os.path.join(jdir, "journal-driver.jsonl"))
        by_type = {}
        for e in events:
            by_type.setdefault(e["type"], []).append(e)
        admitted = {e["slice"] for e in by_type["slice_admitted"]}
        assert admitted == {"pod0", "pod1"}
        lost = by_type["slice_lost"]
        assert len(lost) == 1 and lost[0]["slice"] == "pod0"
        assert lost[0]["cause"] == "preempt"
        assert lost[0]["hosts"] == ["h0", "h1", "h2", "h3"]
        bl = [e for e in by_type["blacklist"]
              if e.get("slice") == "pod0"]
        assert len(bl) == 4


# -- host.preempt seam ----------------------------------------------

_IGNORE_TERM = ("import signal, time; "
                "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                "time.sleep(30)")
_OBEY_TERM = "import time; time.sleep(30)"


def _add_slot(drv, host, local_rank, rank, code):
    p = subprocess.Popen([sys.executable, "-c", code])
    info = RankInfo(rank=rank, size=2, local_rank=local_rank,
                    local_size=1, cross_rank=rank, cross_size=2,
                    host=host)
    drv.slots[(host, local_rank)] = _Slot(info, p)
    return p


class TestPreemptSeam:
    def test_host_selector_targets_only_tagged_host(self, mkdriver):
        drv, _ = mkdriver([HostSlots("hA", 1), HostSlots("hB", 1)])
        p_a = p_b = None
        try:
            p_a = _add_slot(drv, "hA", 0, 0, _OBEY_TERM)
            p_b = _add_slot(drv, "hB", 0, 1, _OBEY_TERM)
            faults.configure("host.preempt:preempt:at=1,host=hB", 0)
            drv._check_preempt_faults()
            assert ("hB", 0) in drv._preempt_pending
            assert ("hA", 0) not in drv._preempt_pending
            assert p_b.wait(timeout=10) == -signal.SIGTERM
            assert p_a.poll() is None
        finally:
            faults.configure(None)
            for p in (p_a, p_b):
                if p is not None and p.poll() is None:
                    p.kill()

    def test_sigterm_then_sigkill_after_grace(self, mkdriver):
        """XLA's preemption notifier catches SIGTERM without exiting;
        the reaper must model the VM poweroff with SIGKILL."""
        drv, _ = mkdriver([HostSlots("hA", 1)])
        drv.preempt_grace = 0.3
        p = None
        try:
            p = _add_slot(drv, "hA", 0, 0, _IGNORE_TERM)
            # let the child install its TERM handler first
            time.sleep(1.0)
            faults.configure("host.preempt:preempt:at=1,host=hA", 0)
            drv._check_preempt_faults()
            assert ("hA", 0) in drv._preempt_pending
            time.sleep(0.1)
            assert p.poll() is None  # survived the SIGTERM storm
            deadline = time.time() + 10
            while p.poll() is None and time.time() < deadline:
                drv._reap_preempted()
                time.sleep(0.05)
            assert p.poll() == -signal.SIGKILL
        finally:
            faults.configure(None)
            if p is not None and p.poll() is None:
                p.kill()

    def test_reaper_drops_stale_keys(self, mkdriver):
        drv, _ = mkdriver([HostSlots("hA", 1)])
        drv._preempt_pending[("hA", 0)] = time.time() - 1
        drv._reap_preempted()  # slot gone: entry must not linger
        assert drv._preempt_pending == {}

    def test_host_param_rejected_at_untagged_point(self):
        with pytest.raises(ValueError):
            faults.parse("wire.send:delay:ms=5,host=h1")

    def test_gang_restart_clears_pending(self, mkdriver):
        drv, _ = mkdriver([HostSlots("localhost", 1)])
        drv._preempt_pending[("localhost", 0)] = time.time() + 99
        drv._hung_pending[("localhost", 0)] = 1.0
        drv._gang_restart()
        assert drv._preempt_pending == {}
        assert drv._hung_pending == {}


# -- live preemption-storm soak -------------------------------------

def _storm_env(tmp_path, jdir):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_LOG"] = os.path.join(str(tmp_path), "progress")
    env["HOROVOD_JOURNAL_DIR"] = str(jdir)
    env["HOROVOD_FAULTS_SEED"] = "14"
    env["HOROVOD_ELASTIC_PREEMPT_GRACE"] = "1"
    env["HOROVOD_ELASTIC_TEARDOWN_GRACE"] = "1"
    return env


def _driver_events(jdir):
    events, _ = journal.read_journal(
        os.path.join(str(jdir), "journal-driver.jsonl"))
    return events


@pytest.mark.integration
def test_preempt_recovery_is_slice_atomic(tmp_path,
                                          multiproc_data_plane):
    """Tier-1 representative: preempt one host of a two-slice world;
    the journal must show the whole slice lost (cause preempt) and
    the job must still complete after re-admission."""
    jdir = tmp_path / "journal"
    jdir.mkdir()
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\n"
                      "echo '127.0.0.1:1 slice=a'\n"
                      "echo '127.0.0.2:1 slice=b'\n")
    script.chmod(0o755)
    env = _storm_env(tmp_path, jdir)
    env["ELASTIC_TEST_STEPS"] = "30"
    env["ELASTIC_TEST_SLEEP"] = "0.2"
    env["HOROVOD_ELASTIC_BLACKLIST_WINDOW"] = "6"
    env["HOROVOD_FAULTS"] = "host.preempt:preempt:at=40,host=127.0.0.1"
    p = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", str(script),
         "--min-num-proc", "1",
         "--host-change-detection-interval", "0.5",
         sys.executable, os.path.join("tests", "elastic_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out, _ = p.communicate(timeout=420)
    assert p.returncode == 0, out
    events = _driver_events(jdir)
    lost = [e for e in events if e["type"] == "slice_lost"]
    assert lost and lost[0]["slice"] == "a" and \
        lost[0]["cause"] == "preempt", lost
    detects = [e for e in events if e["type"] == "detect"]
    assert any(e["cause"] == "preempt" and e.get("slice") == "a"
               for e in detects), detects
    admitted = [e for e in events if e["type"] == "slice_admitted"
                and e["slice"] == "a"]
    assert len(admitted) >= 2, admitted  # initial + re-admission


def _run_preempt_storm(workdir, steps=150, sleep=0.25,
                       storm1=150, storm2=380):
    """The r14 soak: a 4-host / 2-slice world (loopback aliases stand
    in for hosts); both hosts of slice a are preemption-stormed at
    the same driver tick mid-run, then slice b after a has been
    re-admitted. Control-plane-only worker (journal_chaos_worker.py)
    so the soak runs on jaxlib builds without multiprocess
    collectives — the container the committed artifact is generated
    in. Returns (rc, out, jdir)."""
    jdir = os.path.join(workdir, "journal")
    os.makedirs(jdir, exist_ok=True)
    script = os.path.join(workdir, "discover.sh")
    with open(script, "w") as f:
        f.write("#!/bin/sh\n"
                "echo '127.0.0.1:1 slice=a'\n"
                "echo '127.0.0.2:1 slice=a'\n"
                "echo '127.0.0.3:1 slice=b'\n"
                "echo '127.0.0.4:1 slice=b'\n")
    os.chmod(script, 0o755)
    env = _storm_env(workdir, jdir)
    env["ELASTIC_TEST_LOG"] = os.path.join(workdir, "progress")
    env["ELASTIC_TEST_STEPS"] = str(steps)
    env["ELASTIC_TEST_SLEEP"] = str(sleep)
    env["HOROVOD_ELASTIC_BLACKLIST_WINDOW"] = "10"
    # Both hosts of a slice storm at the same per-host tick, so the
    # slice dies as a unit; slice b's storm lands after slice a's
    # blacklist window has expired and a is back (otherwise evicting
    # b would be refused by the min_np capacity guard).
    env["HOROVOD_FAULTS"] = ";".join([
        f"host.preempt:preempt:at={storm1},host=127.0.0.1",
        f"host.preempt:preempt:at={storm1},host=127.0.0.2",
        f"host.preempt:preempt:at={storm2},host=127.0.0.3",
        f"host.preempt:preempt:at={storm2},host=127.0.0.4",
    ])
    p = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", script,
         "--min-num-proc", "2",
         "--host-change-detection-interval", "0.5",
         sys.executable,
         os.path.join("tests", "journal_chaos_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out, _ = p.communicate(timeout=560)
    return p.returncode, out, jdir


def _check_storm_report(report):
    s = report["summary"]
    assert s["recoveries"] >= 2, s
    assert s["by_cause"].get("preempt", 0) >= 2, s
    assert s["by_slice"].get("a", 0) >= 1, s
    assert s["by_slice"].get("b", 0) >= 1, s
    assert s["complete_decompositions"] == s["recoveries"], s
    assert s["committed_step_loss_total"] == 0, s
    for rec in report["recoveries"]:
        assert rec["cause"]["slice"] in ("a", "b"), rec["cause"]
        assert rec["cause"]["seam"] == "host.preempt:preempt", rec
        assert rec["steps"]["committed_step_loss"] == 0, rec
        assert rec["slices_lost"], rec
        for ph in ("detect", "teardown", "rendezvous", "respawn",
                   "restore", "first_commit"):
            assert rec["phases"][ph] is not None, (ph, rec)


@pytest.mark.nightly
def test_whole_slice_preemption_storm_soak(tmp_path):
    """Live seeded soak (the committed artifact's shape, fresh run):
    two whole-slice preemption storms, each detected as preempt,
    blacklisted slice-atomically, re-admitted as a unit, with zero
    committed-step loss at the durable watermark."""
    rc, out, jdir = _run_preempt_storm(str(tmp_path))
    assert rc == 0, out
    _check_storm_report(journal.incident_report(jdir))


class TestCommittedPreemptArtifact:
    """Acceptance pin: the committed preemption-storm artifact holds
    >= 2 whole-slice preempt recoveries with complete decompositions,
    zero committed-step loss, each attributed to its lost slice — and
    regenerates byte-identically from the committed journals."""

    def test_regenerates_byte_identically(self, tmp_path):
        out = str(tmp_path / "regen.json")
        journal.write_incident_report(ARTIFACT_DIR, out=out)
        assert open(out, "rb").read() == open(ARTIFACT, "rb").read()
        assert open(os.path.join(
            ARTIFACT_DIR, "incident_report.json"), "rb").read() == \
            open(ARTIFACT, "rb").read()

    def test_acceptance_invariants(self):
        report = json.load(open(ARTIFACT))
        _check_storm_report(report)
        assert report["source"]["faults"][0]["seed"] == 14
        assert "host.preempt:preempt" in \
            report["source"]["faults"][0]["spec"]


if __name__ == "__main__":
    # Artifact generation (run manually; see docs/benchmarks.md):
    #   python tests/test_slices.py /tmp/storm-work
    import shutil
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/preempt_r14"
    os.makedirs(work, exist_ok=True)
    rc, out, jdir = _run_preempt_storm(work)
    print(out)
    print("rc =", rc)
    if rc != 0:
        sys.exit(1)
    report = journal.incident_report(jdir)
    _check_storm_report(report)
    os.makedirs(ARTIFACT_DIR, exist_ok=True)
    for name in sorted(os.listdir(jdir)):
        if name.startswith("journal-"):
            shutil.copy(os.path.join(jdir, name),
                        os.path.join(ARTIFACT_DIR, name))
    journal.write_incident_report(ARTIFACT_DIR, out=ARTIFACT)
    journal.write_incident_report(ARTIFACT_DIR)
    print("committed artifact written:", ARTIFACT)
