"""2-rank numerics chaos worker: a fixed-seed eager training loop
under the coordinated skip-step guard. The test arms
HOROVOD_FAULTS="numerics.grad:nan:at=N,rank=1" so ONE rank sees ONE
NaN gradient pre-reduction; the finite-flag riding the fused allreduce
must turn it into the SAME single skip on every rank, leaving
post-run parameters bitwise identical everywhere. Each rank asserts
its own skip counter and the cross-rank digest agreement, then prints
a line the test greps."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import numerics  # noqa: E402

STEPS = int(os.environ.get("NUMERICS_TEST_STEPS", "6"))
EXPECT_SKIPS = int(os.environ.get("NUMERICS_TEST_EXPECT_SKIPS", "1"))


def main():
    hvd.init()
    assert numerics.guard_enabled(), \
        "worker must be launched with HOROVOD_NUMERICS_GUARD=1"
    opt = hvd.DistributedOptimizer(
        numerics.guard_non_finite(optax.sgd(0.1)))
    params = {"w": jnp.arange(4.0, dtype=jnp.float32)}
    opt_state = opt.init(params)

    for step in range(STEPS):
        # Deterministic, rank-INDEPENDENT gradients (of
        # 0.5*||w - t||^2), so replicas only stay bitwise identical if
        # the injected rank-local NaN skips on EVERY rank.
        target = jnp.full(4, float(step + 1), jnp.float32)
        grads = {"w": params["w"] - target}
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        assert bool(numerics.all_finite(params)), \
            f"params poisoned at step {step}"

    snap = hvd.metrics()
    skipped = int(sum(
        (snap.get("hvd_skipped_steps_total") or {}).values()))
    assert skipped == EXPECT_SKIPS, (skipped, EXPECT_SKIPS)
    assert numerics.consecutive_skips(opt_state) == 0

    digest = numerics.params_digest(params)
    digests = hvd.allgather_object(digest, name="final.digest")
    assert len(set(digests)) == 1, \
        f"replicas diverged: {[hex(d) for d in digests]}"

    # sanity: the run actually trained (a skip-everything run would
    # leave w at its init)
    assert not np.allclose(np.asarray(params["w"]), np.arange(4.0))

    print(f"numerics ok rank {hvd.rank()} skips {skipped} "
          f"digest {digest:#018x}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
