"""Timeline (Chrome-trace output) + autotuner behavior tests
(reference subsystems: horovod/common/timeline.cc,
horovod/common/parameter_manager.cc)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.autotune import CYCLE_GRID, FUSION_GRID, Autotuner
from horovod_tpu.common.config import Config
from horovod_tpu.timeline import Timeline


class TestTimeline:
    def test_valid_chrome_trace(self, tmp_path):
        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        tl.enqueue("t1")
        tl.dispatched("t1")
        tl.done("t1")
        tl.enqueue("t2")
        tl.error("t2")
        tl.close()
        events = json.load(open(path))
        assert isinstance(events, list) and events
        names = {e["name"] for e in events}
        assert {"QUEUE", "DISPATCH"} <= names
        # spans balanced per (tid, name)
        opens = {}
        for e in events:
            key = (e.get("tid"), e["name"])
            if e["ph"] == "B":
                opens[key] = opens.get(key, 0) + 1
            elif e["ph"] == "E":
                opens[key] = opens.get(key, 0) - 1
        assert all(v == 0 for v in opens.values()), opens

    def test_runtime_start_stop(self, tmp_path, hvd_single):
        path = str(tmp_path / "rt.json")
        hvd_single.start_timeline(path)
        hvd_single.allreduce(jnp.ones(4), name="tl_op")
        hvd_single.stop_timeline()
        events = json.load(open(path))
        metas = [e for e in events if e["ph"] == "M"]
        # lane-name metadata plus the trace-correlation records
        # (hvd_trace_meta carries the monotonic clock anchor)
        assert any(m["args"].get("name") == "tl_op" for m in metas)
        assert any(m["name"] == "hvd_trace_meta" for m in metas)


def make_tuner(**over):
    overrides = {"HOROVOD_AUTOTUNE": True,
                 "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": 1,
                 "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": 2}
    overrides.update(over)
    return Autotuner(Config(overrides, env={}))


class TestAutotuner:
    def test_warmup_discarded_then_steps(self):
        t = make_tuner()
        start = (t.fusion_threshold, t.cycle_time_ms)
        # warmup sample: no knob movement
        t.record(100, 0.001)
        t.record(100, 0.001)
        assert (t.fusion_threshold, t.cycle_time_ms) == start
        # first real sample moves a knob along its grid
        t.record(100, 0.001)
        t.record(100, 0.001)
        assert (t.fusion_threshold, t.cycle_time_ms) != start
        assert t.fusion_threshold in FUSION_GRID
        assert t.cycle_time_ms in CYCLE_GRID

    def test_reverts_on_worse_score(self):
        t = make_tuner()
        for _ in range(2):   # warmup
            t.record(1000, 0.001)
        for _ in range(2):   # good sample at start point
            t.record(1000, 0.001)
        good = t._best
        for _ in range(2):   # much worse sample at the new point
            t.record(1, 1.0)
        assert t._best == good
        # current point reverted to best before stepping again
        assert t._best_score > 0

    def test_log_csv(self, tmp_path):
        path = str(tmp_path / "at.csv")
        t = make_tuner(HOROVOD_AUTOTUNE_LOG=path)
        for _ in range(6):
            t.record(500, 0.001)
        lines = open(path).read().splitlines()
        assert lines[0].startswith("fusion_threshold,")
        assert len(lines) >= 2

    def test_wired_through_controller(self):
        """End-to-end: autotune on + forced controller; knobs move and
        the core's threshold AND cycle time follow (round-1 verdict:
        tuned cycle_time_ms was never propagated — half the search
        space was dead)."""
        import horovod_tpu as hvd
        from horovod_tpu.common.basics import state
        hvd.init(config_overrides={
            "HOROVOD_CONTROLLER": "native",
            "HOROVOD_AUTOTUNE": True,
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": 0,
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": 1})
        try:
            st = state()
            if st.engine.controller is None:
                pytest.skip("no controller")
            assert st.autotuner is not None
            for i in range(10):
                hvd.allreduce(jnp.ones(16), name=f"at{i}")
            assert len(st.autotuner._samples) >= 9
            ctrl = st.engine.controller
            # after every dispatched batch the controller syncs the
            # tuner's current point into the native core
            assert ctrl._pushed_fusion == st.autotuner.fusion_threshold
            assert ctrl._pushed_cycle == st.autotuner.cycle_time_ms
            assert ctrl._pushed_quiesce == st.autotuner.quiescence
            # the hill-climb must have exercised the cycle knob too
            visited_cycles = {c for _, c, _, _ in st.autotuner._samples}
            assert len(visited_cycles) > 1, (
                "cycle knob never moved", st.autotuner._samples)
        finally:
            hvd.shutdown()


class TestGPAutotuner:
    """Gaussian-process Bayesian mode (reference:
    parameter_manager.cc BayesianParameter +
    utils/gaussian_process.cc / bayesian_optimization.cc)."""

    def test_gp_search_finds_synthetic_optimum(self):
        import numpy as np
        from horovod_tpu.autotune import GaussianProcessSearch
        # 1-D candidates; smooth objective peaked at 0.62.
        cand = np.linspace(0, 1, 41)[:, None]
        gp = GaussianProcessSearch(cand, lengthscale=0.2)
        f = lambda x: -((x - 0.62) ** 2)
        X, y = [[0.0], [1.0]], [f(0.0), f(1.0)]
        for _ in range(10):
            i = gp.suggest(np.array(X), np.array(y))
            x = float(cand[i, 0])
            X.append([x]); y.append(f(x))
        best_x = X[int(np.argmax(y))][0]
        assert abs(best_x - 0.62) < 0.08, best_x

    def test_gp_mode_converges_on_response_surface(self):
        """Drive the full Autotuner in gp mode against a synthetic
        bytes/sec surface peaked at (8 MiB, 2.5 ms); it must land on
        (or next to) the peak within a modest sample budget."""
        import numpy as np
        from horovod_tpu.autotune import CYCLE_GRID, FUSION_GRID
        t = make_tuner(HOROVOD_AUTOTUNE_MODE="gp")
        assert t.mode == "gp"
        _MB = 1024 * 1024

        def surface(fusion, cycle):
            lf = np.log2(fusion + 1.0)
            return 1e9 * np.exp(-0.5 * ((lf - np.log2(8 * _MB)) ** 2
                                        / 4.0
                                        + (np.log(cycle)
                                           - np.log(2.5)) ** 2 / 1.0))

        t.record(1, 1.0)
        t.record(1, 1.0)   # warmup sample, discarded
        for _ in range(40):
            score = surface(t.fusion_threshold, t.cycle_time_ms)
            # two events -> one sample at the current knob point;
            # record() scores bytes/seconds, so feed score as bytes
            # over 1 second split across the two events.
            t.record(int(score / 2), 0.5)
            t.record(int(score / 2), 0.5)
        bf, bc, _ = t.best()
        fi = FUSION_GRID.index(bf)
        ci = CYCLE_GRID.index(bc)
        assert abs(fi - FUSION_GRID.index(8 * _MB)) <= 1, (bf, bc)
        assert abs(ci - CYCLE_GRID.index(2.5)) <= 1, (bf, bc)

    def test_gp_mode_finds_quiescence_optimum(self):
        """The third search dimension (round-4 addition): a surface
        that rewards quiescence=5 must pull the tuner there — the
        hook-storm scenario where composition stability dominates."""
        import numpy as np
        from horovod_tpu.autotune import QUIESCE_GRID
        t = make_tuner(HOROVOD_AUTOTUNE_MODE="gp")

        def surface(q):
            return 1e9 * np.exp(-0.5 * (q - 5.0) ** 2 / 4.0)

        t.record(1, 1.0)
        t.record(1, 1.0)   # warmup
        for _ in range(50):
            score = surface(t.quiescence)
            t.record(int(score / 2), 0.5)
            t.record(int(score / 2), 0.5)
        _, _, bq = t.best()
        qi = QUIESCE_GRID.index(bq)
        assert abs(qi - QUIESCE_GRID.index(5)) <= 1, t.best()

    def test_bad_mode_rejected(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="AUTOTUNE_MODE"):
            make_tuner(HOROVOD_AUTOTUNE_MODE="annealing")
