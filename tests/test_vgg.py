"""VGG-16: the reference benchmark trio's comm-bound member
(reference: docs/benchmarks.rst VGG-16 ~68% scaling because ~138M
params are gradient-wire-heavy)."""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import create_vgg16, init_vgg


def test_vgg16_param_count_and_forward():
    model = create_vgg16(dtype=jnp.float32)
    variables = init_vgg(model, jax.random.PRNGKey(0), image_size=224)
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    # canonical VGG-16 (config D, 1000 classes): 138,357,544 params
    assert n == 138_357_544, n

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 224, 224, 3))
    logits = model.apply(variables, x, train=True)
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32


def test_vgg16_small_image_trains():
    """The classifier infers its input width, so small-image CI runs
    exercise the same code path; one SGD step reduces the loss on a
    fixed batch."""
    import optax
    model = create_vgg16(num_classes=10, dtype=jnp.float32)
    variables = init_vgg(model, jax.random.PRNGKey(0), image_size=32)
    params = variables["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    y = jnp.array([0, 1, 2, 3])

    def loss_fn(p):
        logits = model.apply({"params": p}, x, train=True)
        onehot = jax.nn.one_hot(y, 10)
        return jnp.mean(-jnp.sum(
            onehot * jax.nn.log_softmax(logits), axis=-1))

    opt = optax.sgd(0.01)
    state = opt.init(params)
    l0, grads = jax.value_and_grad(loss_fn)(params)
    updates, state = opt.update(grads, state, params)
    params = optax.apply_updates(params, updates)
    l1 = loss_fn(params)
    assert float(l1) < float(l0), (float(l0), float(l1))
