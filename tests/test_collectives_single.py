"""Single-process collective semantics: identity paths, scaling,
dtype handling, error cases
(reference analog: the size==1 paths of test/parallel/test_torch.py)."""

import jax.numpy as jnp
import numpy as np
import pytest


@pytest.mark.parametrize("dtype", ["float32", "float64", "bfloat16",
                                   "float16", "int32", "int64", "uint8"])
def test_allreduce_identity(hvd_single, dtype):
    hvd = hvd_single
    x = jnp.arange(12, dtype=dtype).reshape(3, 4)
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert out.dtype == x.dtype


def test_allreduce_average_int_raises(hvd_single):
    hvd = hvd_single
    with pytest.raises(ValueError, match="Average"):
        hvd.allreduce(jnp.arange(4), op=hvd.Average)


def test_allreduce_scaling(hvd_single):
    hvd = hvd_single
    x = jnp.ones((4,), jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=3.0)
    np.testing.assert_allclose(np.asarray(out), 6 * np.ones(4), rtol=1e-6)


def test_allreduce_average_float(hvd_single):
    hvd = hvd_single
    x = jnp.ones((4,), jnp.float32) * 5
    out = hvd.allreduce(x)  # default Average
    np.testing.assert_allclose(np.asarray(out), 5 * np.ones(4))


def test_allreduce_op_and_average_conflict(hvd_single):
    hvd = hvd_single
    with pytest.raises(ValueError, match="either op or average"):
        hvd.allreduce(jnp.ones(3), average=True, op=hvd.Sum)


def test_grouped_allreduce(hvd_single):
    hvd = hvd_single
    ts = [jnp.ones((3,)), jnp.arange(4, dtype=jnp.float32),
          jnp.ones((2, 2), jnp.int32)]
    outs = hvd.grouped_allreduce(ts, op=hvd.Sum)
    assert len(outs) == 3
    for t, o in zip(ts, outs):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(t))
        assert o.dtype == t.dtype


def test_allgather_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    out = hvd.allgather(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_broadcast_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(4)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_broadcast_bad_root(hvd_single):
    hvd = hvd_single
    with pytest.raises(ValueError, match="root_rank"):
        hvd.broadcast(jnp.ones(2), root_rank=3)


def test_alltoall_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(8, dtype=jnp.float32)
    out = hvd.alltoall(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_alltoall_bad_splits(hvd_single):
    hvd = hvd_single
    with pytest.raises(ValueError, match="splits must sum"):
        hvd.alltoall(jnp.arange(8.0), splits=[3])


def test_reducescatter_single(hvd_single):
    hvd = hvd_single
    x = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    out = hvd.reducescatter(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_grouped_allgather_single(hvd_single):
    """One handle over N allgathers, results in submission order
    (reference: grouped_allgather)."""
    hvd = hvd_single
    xs = [jnp.ones((2, 3)), jnp.arange(4.0), jnp.ones((1,), jnp.int32)]
    outs = hvd.grouped_allgather(xs, name="gag")
    assert isinstance(outs, list) and len(outs) == 3
    for x, o in zip(xs, outs):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(x))
    assert outs[2].dtype == jnp.int32


def test_grouped_reducescatter_single(hvd_single):
    hvd = hvd_single
    xs = [jnp.ones((4, 2)), jnp.full((2,), 3.0)]
    outs = hvd.grouped_reducescatter(xs, op=hvd.Sum, name="grs")
    assert len(outs) == 2
    np.testing.assert_array_equal(np.asarray(outs[0]),
                                  np.asarray(xs[0]))
    np.testing.assert_array_equal(np.asarray(outs[1]),
                                  np.asarray(xs[1]))


def test_grouped_handle_drains_children_on_error(hvd_single):
    """A failing child must not strand its siblings: the composite
    synchronize drains every child (releasing engine handles) before
    re-raising, and the error is sticky."""
    import pytest
    from horovod_tpu.ops.collective_ops import GroupedHandle
    hvd = hvd_single
    good = hvd.allgather_async(jnp.ones(3), name="drain.good")
    h = GroupedHandle("drain", [good, 999999999])
    with pytest.raises(KeyError):
        h.synchronize()
    with pytest.raises(KeyError):   # sticky, not a new probe
        h.synchronize()
    # the good child was drained: its handle is released, so a direct
    # synchronize now raises (already collected), not hangs
    with pytest.raises(KeyError):
        hvd.synchronize(good)


def test_barrier_single(hvd_single):
    hvd_single.barrier()


def test_async_poll_synchronize(hvd_single):
    hvd = hvd_single
    h = hvd.allreduce_async(jnp.ones((1000,)), op=hvd.Sum)
    out = hvd.synchronize(h)
    np.testing.assert_array_equal(np.asarray(out), np.ones(1000))


def test_compression_fp16_roundtrip(hvd_single):
    hvd = hvd_single
    x = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum, compression=hvd.Compression.fp16)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-2)


def test_compression_bf16_roundtrip(hvd_single):
    hvd = hvd_single
    x = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum, compression=hvd.Compression.bf16)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=5e-2)


def test_allgather_object_single(hvd_single):
    import horovod_tpu as hvd
    assert hvd.allgather_object({"a": [1, 2]}) == [{"a": [1, 2]}]


def test_broadcast_object_single(hvd_single):
    from horovod_tpu.optim.functions import broadcast_object
    obj = {"epoch": 3, "name": "x"}
    assert broadcast_object(obj, root_rank=0) == obj


def test_broadcast_parameters_single(hvd_single):
    from horovod_tpu.optim.functions import broadcast_parameters
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = broadcast_parameters(params, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones((3, 3)))
