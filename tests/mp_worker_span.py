"""Worker for the device-spanning eager data plane test: every
process owns SEVERAL devices (xla_force_host_platform_device_count>1
per process — the CPU stand-in for a multi-chip TPU host, SURVEY.md §4
technique 2), and the classic eager allreduce must reduce over ALL of
them, not one representative per process (round-3 verdict Missing #1).

Asserts on the mesh (every device of every process participates) and
on the summed payload (results correct through the wide kernel,
with and without fp16 compression, grouped and single)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Each PROCESS gets several virtual devices (set by the launching
# test via XLA_FLAGS; default here for direct runs).
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.basics import state  # noqa: E402
from horovod_tpu.ops import dispatch  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    ndev_local = len(jax.local_devices())
    assert ndev_local > 1, (
        f"test setup: expected >1 local device, got {ndev_local}")

    st = state()
    pset = st.engine.pset_table.get(0)

    # 1) the device-spanning mesh covers EVERY device of EVERY process.
    dm = pset.device_mesh
    assert dm is not None, "device_mesh must exist with >1 local device"
    assert dict(dm.shape) == {"proc": n, "dev": ndev_local}, dm.shape
    assert int(dm.devices.size) == len(jax.devices()) == n * ndev_local
    procs_in_mesh = {d.process_index for d in dm.devices.flat}
    assert procs_in_mesh == set(range(n)), procs_in_mesh
    print(f"rank {r}: device mesh spans {int(dm.devices.size)} devices")

    # 2) big eager allreduce lands on the wide path and is correct.
    elems = 4096  # >= ndev * _WIDE_MIN_ELEMS_PER_DEV
    x = jnp.arange(elems, dtype=jnp.float32) + float(r)
    out = hvd.allreduce(x, name="span_sum", op=hvd.Sum)
    info = dispatch.last_allreduce_info()
    assert info.get("path") == "wide", info
    assert info.get("devices") == n * ndev_local, info
    expect = np.arange(elems, dtype=np.float32) * n + sum(range(n))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)
    print(f"rank {r}: wide allreduce OK ({info})")

    # 3) grouped + fp16 compression through the wide kernel: the cast
    # folds into the same launch; MIXED raw dtypes (bf16 + f32) share
    # the fp16 wire and fuse into ONE wide program (wire-keyed fuse
    # rule), each output restored to its raw dtype.
    xs = [jnp.full((2048,), float(i + 1 + r),
                   jnp.bfloat16 if i % 2 else jnp.float32)
          for i in range(4)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Average,
                                 compression=hvd.Compression.fp16)
    info = dispatch.last_allreduce_info()
    assert info.get("path") == "wide", info
    for i, o in enumerate(outs):
        assert o.dtype == (jnp.bfloat16 if i % 2 else jnp.float32), \
            (i, o.dtype)
        expect_v = sum(float(i + 1 + rr) for rr in range(n)) / n
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   np.full(2048, expect_v), rtol=3e-2)
    print(f"rank {r}: wide grouped+fp16 mixed-raw OK")

    # 4) small payloads stay on the flat path (auto floor) and agree.
    out = hvd.allreduce(jnp.full((8,), 1.0), name="small", op=hvd.Sum)
    info = dispatch.last_allreduce_info()
    assert info.get("path") == "flat", info
    np.testing.assert_allclose(np.asarray(out), np.full(8, float(n)))
    print(f"rank {r}: small-payload flat fallback OK")

    # 4.5) broadcast through the wide kernel: rank 0's bucket reaches
    # every rank with each chip moving 1/D of it (broadcast_parameters
    # is the startup whole-model move — it must span chips too).
    # non-root ranks hold GARBAGE, not zeros: a dropped root mask in
    # the kernel (degenerating to a plain sum) must fail this assert.
    big = (jnp.arange(4096, dtype=jnp.float32) if r == 0
           else jnp.full((4096,), -7.0 * (r + 1), jnp.float32))
    out = hvd.broadcast(big, root_rank=0, name="span_bcast")
    np.testing.assert_allclose(
        np.asarray(out), np.arange(4096, dtype=np.float32))
    print(f"rank {r}: wide broadcast OK")

    # 5) min/max through the wide kernel too.
    out = hvd.allreduce(jnp.full((4096,), float(r + 1)), name="span_max",
                        op=hvd.Max)
    assert dispatch.last_allreduce_info().get("path") == "wide"
    np.testing.assert_allclose(np.asarray(out), np.full(4096, float(n)))
    print(f"rank {r}: wide max OK")

    # 6) allgather through the wide kernel: ragged first dims, every
    # chip moves 1/D of the bucket (round-4 verdict Missing #1).
    rows_mine = 512 + 16 * r
    xg = jnp.full((rows_mine, 4), float(r), jnp.float32)
    out = hvd.allgather(xg, name="span_ag")
    info = dispatch.last_op_info("allgather")
    assert info.get("path") == "wide", info
    assert info.get("devices") == n * ndev_local, info
    expect_rows = sum(512 + 16 * rr for rr in range(n))
    assert out.shape == (expect_rows, 4), out.shape
    off = 0
    for rr in range(n):
        seg = np.asarray(out[off:off + 512 + 16 * rr])
        np.testing.assert_allclose(seg, np.full(seg.shape, float(rr)))
        off += 512 + 16 * rr
    print(f"rank {r}: wide allgather OK ({info})")

    # 7) reducescatter through the wide kernel: uneven first dim, each
    # rank gets its trimmed reduced block.
    d0 = 4 * n + 1  # uneven: low ranks get one extra row
    xs_rs = jnp.tile(jnp.arange(d0, dtype=jnp.float32)[:, None],
                     (1, 1024)) + float(r)
    out = hvd.reducescatter(xs_rs, name="span_rs", op=hvd.Sum)
    info = dispatch.last_op_info("reducescatter")
    assert info.get("path") == "wide", info
    from horovod_tpu.ops.dispatch import reducescatter_rows
    rows_all = reducescatter_rows(d0, n)
    my_off = sum(rows_all[:r])
    expect = (np.tile(np.arange(d0, dtype=np.float32)[:, None],
                      (1, 1024)) * n + sum(range(n)))
    np.testing.assert_allclose(
        np.asarray(out), expect[my_off:my_off + rows_all[r]], rtol=1e-6)
    print(f"rank {r}: wide reducescatter OK ({info})")

    # 8) alltoall through the wide kernel (uniform splits, padded
    # schedule forced so the wide padded kernel engages).
    from horovod_tpu.ops import dispatch as dsp
    dsp.set_alltoall_mode("padded")
    rows_a2a = 256
    xa = jnp.concatenate([
        jnp.full((rows_a2a, 2), float(r * 10 + dst), jnp.float32)
        for dst in range(n)])
    out, recv = hvd.alltoall(xa, splits=[rows_a2a] * n, name="span_a2a")
    np.testing.assert_array_equal(np.asarray(recv),
                                  np.full(n, rows_a2a))
    info = dispatch.last_op_info("alltoall")
    assert info.get("path") == "wide", info
    for src in range(n):
        seg = np.asarray(out[src * rows_a2a:(src + 1) * rows_a2a])
        np.testing.assert_allclose(
            seg, np.full(seg.shape, float(src * 10 + r)))
    dsp.set_alltoall_mode("auto")
    print(f"rank {r}: wide alltoall OK ({info})")

    # 8b) RAGGED alltoall rounds through the wide kernel too: skewed
    # splits, forced ragged schedule — each ppermute round's chunk
    # slabs across local chips.
    dsp.set_alltoall_mode("ragged")
    splits_r = [256 + 128 * ((r + dst) % 2) for dst in range(n)]
    xa2 = jnp.concatenate([
        jnp.full((splits_r[dst], 2), float(r * 100 + dst), jnp.float32)
        for dst in range(n)])
    out, recv = hvd.alltoall(xa2, splits=splits_r, name="span_a2a_rag")
    info = dispatch.last_op_info("alltoall")
    assert info.get("path") == "ragged", info
    stats = dsp.last_alltoall_stats()
    # every nonzero round must have taken the device-spanning kernel
    # (outputs are identical on the flat rounds — assert the path).
    assert stats.get("wide_rounds") == n - 1, stats
    off = 0
    for src in range(n):
        rows_src = 256 + 128 * ((src + r) % 2)
        assert int(recv[src]) == rows_src, (src, recv)
        seg = np.asarray(out[off:off + rows_src])
        np.testing.assert_allclose(
            seg, np.full(seg.shape, float(src * 100 + r)))
        off += rows_src
    dsp.set_alltoall_mode("auto")
    print(f"rank {r}: ragged wide alltoall OK")

    # 9) Adasum allreduce through the wide vhdd kernel (pow2 worlds) —
    # oracle-checked against the numpy fold.
    from horovod_tpu.ops.adasum import adasum_reference
    rng = np.random.RandomState(17)
    contribs = [rng.randn(3000).astype(np.float32) for _ in range(n)]
    out = hvd.allreduce(jnp.asarray(contribs[r]), name="span_adasum",
                        op=hvd.Adasum)
    info = dispatch.last_op_info("adasum")
    # pow2 AND non-pow2 sets take the device-spanning vhdd (the mixed
    # kernel handles any n via pow2 blocks + merges).
    assert info.get("path") == "vhdd_wide", info
    assert info.get("devices") == n * ndev_local, info
    expect = adasum_reference(contribs)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-4,
                               atol=2e-5)
    print(f"rank {r}: wide adasum OK ({info})")

    hvd.shutdown()
    print(f"rank {r}: SPAN ALL OK")


if __name__ == "__main__":
    main()
