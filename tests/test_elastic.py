"""Elastic integration tests — the reference's key techniques
(SURVEY.md §4): a discovery script that IS a rewritable temp file, and
rank suicide for failure injection. Real subprocesses, no mocks."""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_env(tmp_path, steps=30, sleep=0.2):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_LOG"] = str(tmp_path / "progress")
    env["ELASTIC_TEST_STEPS"] = str(steps)
    env["ELASTIC_TEST_SLEEP"] = str(sleep)
    return env


def write_discovery(tmp_path, content):
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\n{content}\n")
    script.chmod(0o755)
    return script


def read_logs(tmp_path):
    lines = []
    for p in tmp_path.glob("progress.*"):
        lines += p.read_text().splitlines()
    return lines


def launch(script, env, extra=(), worker="elastic_worker.py"):
    return subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", str(script),
         "--min-num-proc", "1",
         "--host-change-detection-interval", "0.5",
         *extra,
         sys.executable, os.path.join("tests", worker)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.integration
class TestElastic:
    def test_unit_driver_pieces(self, tmp_path):
        """Discovery parse + rendezvous endpoints (no processes)."""
        from horovod_tpu.runner.elastic import (HostDiscoveryScript,
                                                RendezvousServer)
        s = write_discovery(tmp_path, "echo localhost:2")
        d = HostDiscoveryScript(str(s))
        hosts = d.find_available_hosts_and_slots()
        assert [(h.host, h.slots) for h in hosts] == [("localhost", 2)]

        rs = RendezvousServer()
        rs.publish(1, {("localhost", 0): {"HOROVOD_RANK": "0"}})
        import urllib.request
        with urllib.request.urlopen(
                f"http://localhost:{rs.port}/rank/localhost/0") as r:
            assert json.loads(r.read()) == {"HOROVOD_RANK": "0"}
        with urllib.request.urlopen(
                f"http://localhost:{rs.port}/world") as r:
            assert json.loads(r.read())["epoch"] == 1
        req = urllib.request.Request(
            f"http://localhost:{rs.port}/notify/localhost/0",
            data=b'{"port": 1234}', method="PUT")
        urllib.request.urlopen(req).read()
        assert rs.notify_ports() == {("localhost", 0): 1234}
        rs.stop()

    def test_static_elastic_run_completes(self, tmp_path):
        script = write_discovery(tmp_path, "echo localhost:2")
        env = make_env(tmp_path, steps=6, sleep=0.05)
        p = launch(script, env)
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) == 2, lines
        assert any("world 2" in ln for ln in lines)

    def _scale_up(self, tmp_path, worker, steps):
        """Shared scale-up sequence: start at 2 procs, grow the
        discovery file to 3 once 2-proc progress is OBSERVED (a fixed
        sleep races worker startup on a loaded machine), assert
        committed progress never regresses below the resize point."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:2\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=steps, sleep=0.25)
        p = launch(script, env, worker=worker)
        try:
            deadline = time.time() + 240
            while time.time() < deadline:
                if any("world 2" in ln for ln in read_logs(tmp_path)):
                    break
                if p.poll() is not None:
                    break
                time.sleep(0.5)
            hosts_file.write_text("localhost:3\n")
            out, _ = p.communicate(timeout=420)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert any("world 2" in ln for ln in lines), (lines, out)
        assert any("world 3" in ln for ln in lines), (lines, out)
        dones = [ln for ln in lines if "done" in ln]
        assert len(dones) == 3, (dones, out)
        # committed steps never regress below the resize point: the
        # max step logged in world 2 must be <= min step logged by the
        # new world's rank 0 continuation + 1
        w2 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 2" in ln]
        w3 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 3" in ln]
        assert w2 and w3 and min(w3) >= max(w2) - 1, (max(w2), min(w3))

    def test_graceful_scale_up(self, tmp_path):
        """Start at 2 procs; mid-run the discovery file grows to 3;
        workers resize without losing committed progress."""
        self._scale_up(tmp_path, "elastic_worker.py", steps=40)

    def test_torch_frontend_elastic_scale_up(self, tmp_path):
        """The torch frontend rides the same elastic machinery:
        TorchState + hook optimizer survive a mid-run scale-up with
        committed progress intact and identical final weights (the
        worker asserts weight agreement before logging done).
        steps=40 like the jax variant: the respawned workers pay
        torch-import startup, and fewer steps can run out before the
        new world-3 member joins on a loaded host (observed flake)."""
        self._scale_up(tmp_path, "elastic_worker_torch.py", steps=40)

    def test_resize_rebuilds_wide_mesh(self, tmp_path):
        """Elastic resize x multi-chip processes: after a scale-down,
        the device-spanning ('proc','dev') eager path must rebuild
        for the NEW world size (the wide-mesh caches live on
        ProcessSet instances that re-init replaces) — every step
        asserts path == wide with the current world in the mesh."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:3\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=30, sleep=0.25)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["ELASTIC_TEST_WIDE"] = "1"
        p = launch(script, env)
        try:
            deadline = time.time() + 240
            while time.time() < deadline:
                if any("wide ok world 3" in ln
                       for ln in read_logs(tmp_path)):
                    break
                if p.poll() is not None:
                    break
                time.sleep(0.5)
            hosts_file.write_text("localhost:2\n")
            out, _ = p.communicate(timeout=300)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]  # reap + keep the output
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        # wide engaged at BOTH world sizes, 2 devices per process
        # (the worker asserts mesh_shape == {proc: size, dev: 2} on
        # every step, so one line per size proves the rebuild).
        assert any("wide ok world 3 devs 6" in ln for ln in lines), \
            lines[-10:]
        assert any("wide ok world 2 devs 4" in ln for ln in lines), \
            lines[-10:]

    def test_graceful_scale_down(self, tmp_path):
        """Start at 3 procs; mid-run the discovery file shrinks to 2.
        The removed rank drains voluntarily (clean exit at its commit
        boundary — no SIGTERM mid-collective), survivors resize
        without a gang restart, and committed progress carries over
        (reference: horovod/runner/elastic/driver.py host-removal
        path treats remove symmetrically with add)."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:3\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=40, sleep=0.25)
        env["HOROVOD_LOG_LEVEL"] = "info"
        p = launch(script, env)
        try:
            deadline = time.time() + 240
            while time.time() < deadline:
                if any("world 3" in ln for ln in read_logs(tmp_path)):
                    break
                if p.poll() is not None:
                    break
                time.sleep(0.5)
            hosts_file.write_text("localhost:2\n")
            # Graceful-resize latency ceiling (round-3 verdict Next
            # #9): the shrunken world must be RUNNING within a bound —
            # the drain + re-init path may not lean on a long init
            # timeout. 90 s is generous for this loaded 1-core box;
            # the healthy path takes a few seconds.
            t_shrink = time.time()
            resize_s = None
            while time.time() - t_shrink < 240:
                if any("world 2" in ln for ln in read_logs(tmp_path)):
                    resize_s = time.time() - t_shrink
                    break
                if p.poll() is not None:
                    break
                time.sleep(0.5)
            out, _ = p.communicate(timeout=420)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert any("world 3" in ln for ln in lines), lines
        assert any("world 2" in ln for ln in lines), lines
        assert resize_s is not None and resize_s < 90, (
            f"graceful resize took {resize_s}s (ceiling 90s)")
        # graceful: drain, not failure — no gang restart anywhere
        assert "worker failure" not in out, out
        assert "draining removed rank" in out, out
        # the drained worker exits voluntarily with rc=0
        assert "exited (rc=0)" in out, out
        # exactly the 2 surviving ranks finish the job
        dones = [ln for ln in lines if "done" in ln]
        assert len(dones) == 2, (dones, out)
        assert all("world 2" in ln for ln in dones), dones
        # progress continuity across the shrink: the new world resumes
        # at (or one past) the old world's last committed step
        w3 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 3" in ln]
        w2 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 2" in ln]
        assert w3 and w2 and min(w2) >= max(w3) - 1, (max(w3), min(w2))

    def test_scale_down_then_up_churn(self, tmp_path):
        """Membership churn: 3 -> 2 -> 3. The re-added slot joins the
        running job (fresh process, synced by rank 0) and all three
        ranks complete (reference: remove-then-re-add cycle over the
        same HostsUpdatedInterrupt machinery)."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:3\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=60, sleep=0.25)
        env["HOROVOD_LOG_LEVEL"] = "info"
        p = launch(script, env)
        out = ""
        try:
            def wait_for(pred, timeout=240):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    if pred(read_logs(tmp_path)) or p.poll() is not None:
                        return
                    time.sleep(0.5)

            wait_for(lambda ls: any("world 3" in ln for ln in ls))
            hosts_file.write_text("localhost:2\n")
            wait_for(lambda ls: any("world 2" in ln for ln in ls))
            hosts_file.write_text("localhost:3\n")
            out, _ = p.communicate(timeout=600)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]
            if os.environ.get("ELASTIC_TEST_DUMP"):
                with open(os.environ["ELASTIC_TEST_DUMP"], "w") as f:
                    f.write(out or "")
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert any("world 2" in ln for ln in lines), lines
        assert "worker failure" not in out, out
        # the job ends back at world 3, with all three ranks finishing
        dones = [ln for ln in lines if "done" in ln]
        assert len(dones) == 3, (dones, out)
        assert all("world 3" in ln for ln in dones), dones

    def test_scale_down_below_min_np_is_ignored(self, tmp_path):
        """Discovery shrinking under --min-num-proc must NOT resize
        the job below the floor: the world stays at 3 and completes
        (reference: ElasticDriver honors min_num_proc on the way
        down, not just at startup)."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:3\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=25, sleep=0.25)
        p = launch(script, env, extra=("--min-num-proc", "3"))
        try:
            deadline = time.time() + 240
            while time.time() < deadline:
                if any("world 3" in ln for ln in read_logs(tmp_path)):
                    break
                if p.poll() is not None:
                    break
                time.sleep(0.5)
            hosts_file.write_text("localhost:2\n")
            out, _ = p.communicate(timeout=420)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert not any("world 2" in ln for ln in lines), lines
        dones = [ln for ln in lines if "done" in ln]
        assert len(dones) == 3, (dones, out)

    def test_worker_failure_gang_restart(self, tmp_path):
        """Rank suicide mid-run: the driver restarts the gang and
        training completes (snapshot-level recovery)."""
        script = write_discovery(tmp_path, "echo localhost:2")
        env = make_env(tmp_path, steps=12, sleep=0.2)
        env["ELASTIC_TEST_DIE_AT"] = "4"  # rank 1 exits at step 4
        p = launch(script, env, extra=("--reset-limit", "3"))
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) >= 2, (lines, out)
        # progress preservation: the rank died AFTER logging step 4 but
        # BEFORE committing it, so the snapshot holds step 3 and the
        # restarted gang must resume at step >= 4 — "step 1" may only
        # ever be logged by the first incarnation's 2 ranks.
        step1 = [ln for ln in lines if ln.startswith("step 1 ")]
        assert len(step1) <= 2, (step1, lines)


def test_jax_state_orbax_snapshot_roundtrip(tmp_path, hvd_single):
    """Orbax snapshot backend: async versioned commits, restart-style
    load (SURVEY.md §5.4 'integrate, don't rebuild')."""
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.elastic.state import JaxState
    path = str(tmp_path / "snap")
    st = JaxState(params={"w": jnp.arange(4.0)},
                  opt_state={"m": jnp.zeros(4)},
                  snapshot_path=path, snapshot_backend="orbax",
                  step=0, epoch=0)
    assert not st.maybe_load_snapshot()   # nothing yet; arms writes
    st.params = {"w": jnp.full(4, 7.0)}
    st.step = 3
    st.commit()
    st.params = {"w": jnp.full(4, 9.0)}   # uncommitted progress
    st.step = 4
    st.commit()
    # ensure async write landed before simulating the restart
    st._orbax().wait_until_finished()

    # "restarted gang": fresh state object, same path
    st2 = JaxState(params={"w": jnp.zeros(4)},
                   opt_state={"m": jnp.zeros(4)},
                   snapshot_path=path, snapshot_backend="orbax",
                   step=0, epoch=0)
    assert st2.maybe_load_snapshot()
    np.testing.assert_allclose(np.asarray(st2.params["w"]),
                               np.full(4, 9.0))
    assert st2.step == 4
    # restore() rolls back to the loaded commit
    st2.params = {"w": jnp.full(4, 1.0)}
    st2.restore()
    np.testing.assert_allclose(np.asarray(st2.params["w"]),
                               np.full(4, 9.0))


@pytest.mark.integration
def test_elastic_remote_spawn_via_ssh_shim(tmp_path):
    """Elastic driver's remote-spawn branch through the fake-ssh shim
    (see test_runner._write_fake_ssh): workers on 'fakehost' are
    spawned with the secret on stdin and the full (blocklist-filtered)
    env inlined; the job completes and the secret never rides argv."""
    import socket
    from tests.test_runner import _write_fake_ssh
    _, log = _write_fake_ssh(tmp_path)
    # The real hostname: not in LOCALHOSTS (so the ssh branch fires)
    # but resolvable, which elastic needs — rank 0 lives on the
    # "remote" host and every worker must reach its coordinator.
    host = socket.gethostname()
    script = write_discovery(tmp_path, f"echo {host}:2")
    env = make_env(tmp_path, steps=4, sleep=0.05)
    env["PATH"] = str(tmp_path) + os.pathsep + env["PATH"]
    p = launch(script, env)
    out, _ = p.communicate(timeout=420)
    assert p.returncode == 0, out
    lines = read_logs(tmp_path)
    assert sum("done" in ln for ln in lines) == 2, (lines, out)
    argv = log.read_text()
    assert "HOROVOD_SECRET=" not in argv
    assert "read -r __HVD_ENV" in argv


class TestElasticSampler:
    """Resharding-aware sampler (reference:
    horovod/torch/elastic/sampler.py ElasticSampler) — pure-logic
    tests with the world faked via attributes, the reference suite's
    own technique for sampler coverage."""

    def _mk(self, n=20, rank=0, world=2, shuffle=False):
        # hvd is not initialized in these unit tests, so _reset keeps
        # the injected rank/world (the reference suite fakes the world
        # the same way for sampler coverage).
        from horovod_tpu.elastic.sampler import ElasticSampler
        s = ElasticSampler(n, shuffle=shuffle)
        s.rank, s.world_size = rank, world
        s._reset()
        return s

    def test_even_sharding_no_overlap(self):
        a = self._mk(rank=0)
        b = self._mk(rank=1)
        ia, ib = list(a), list(b)
        assert len(ia) == len(ib) == 10
        assert not set(ia) & set(ib)
        assert sorted(ia + ib) == list(range(20))

    def test_resharding_preserves_unprocessed(self):
        """After processing 2 batches and growing 2 -> 4 ranks, the
        remaining pool is exactly the unprocessed indices, split with
        no repeats across the new world."""
        ranks = [self._mk(rank=r, world=2) for r in range(2)]
        done = []
        for s in ranks:
            s.record_batch(0, 3)
            s.record_batch(1, 3)
            done += s.processed_indices
        assert len(set(done)) == 12
        new = []
        for r in range(4):
            s = ranks[r % 2]
            import copy
            s4 = copy.copy(s)
            s4.processed_indices = list(done)
            s4.rank, s4.world_size = r, 4
            s4.reset_from_state()
            new.append(list(s4))
        flat = [i for idx in new for i in idx]
        assert not set(flat) & set(done)      # nothing repeated
        assert len(set(flat)) == len(flat)    # no cross-rank overlap
        assert set(flat) == set(range(20)) - set(done)  # none dropped

    def test_set_epoch_reshuffles_and_restores_full_pool(self):
        s = self._mk(shuffle=True)
        s.record_batch(0, 5)
        assert len(s.processed_indices) == 5
        order1 = list(s.remaining_indices)
        s.set_epoch(1)
        assert len(s.remaining_indices) == 20
        s2 = self._mk(shuffle=True)
        s2.set_epoch(1)
        assert s.remaining_indices == s2.remaining_indices
        assert s.remaining_indices != order1

    def test_ragged_tail_dropped_evenly(self):
        a = self._mk(n=21, rank=0, world=2)
        b = self._mk(n=21, rank=1, world=2)
        assert len(list(a)) == len(list(b)) == 10
        assert len(a) == 10
