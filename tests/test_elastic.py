"""Elastic integration tests — the reference's key techniques
(SURVEY.md §4): a discovery script that IS a rewritable temp file, and
rank suicide for failure injection. Real subprocesses, no mocks."""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_env(tmp_path, steps=30, sleep=0.2):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_LOG"] = str(tmp_path / "progress")
    env["ELASTIC_TEST_STEPS"] = str(steps)
    env["ELASTIC_TEST_SLEEP"] = str(sleep)
    return env


def write_discovery(tmp_path, content):
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\n{content}\n")
    script.chmod(0o755)
    return script


def read_logs(tmp_path):
    lines = []
    for p in tmp_path.glob("progress.*"):
        lines += p.read_text().splitlines()
    return lines


def launch(script, env, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner",
         "--host-discovery-script", str(script),
         "--min-num-proc", "1",
         "--host-change-detection-interval", "0.5",
         *extra,
         sys.executable, os.path.join("tests", "elastic_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.integration
class TestElastic:
    def test_unit_driver_pieces(self, tmp_path):
        """Discovery parse + rendezvous endpoints (no processes)."""
        from horovod_tpu.runner.elastic import (HostDiscoveryScript,
                                                RendezvousServer)
        s = write_discovery(tmp_path, "echo localhost:2")
        d = HostDiscoveryScript(str(s))
        hosts = d.find_available_hosts_and_slots()
        assert [(h.host, h.slots) for h in hosts] == [("localhost", 2)]

        rs = RendezvousServer()
        rs.publish(1, {("localhost", 0): {"HOROVOD_RANK": "0"}})
        import urllib.request
        with urllib.request.urlopen(
                f"http://localhost:{rs.port}/rank/localhost/0") as r:
            assert json.loads(r.read()) == {"HOROVOD_RANK": "0"}
        with urllib.request.urlopen(
                f"http://localhost:{rs.port}/world") as r:
            assert json.loads(r.read())["epoch"] == 1
        req = urllib.request.Request(
            f"http://localhost:{rs.port}/notify/localhost/0",
            data=b'{"port": 1234}', method="PUT")
        urllib.request.urlopen(req).read()
        assert rs.notify_ports() == {("localhost", 0): 1234}
        rs.stop()

    def test_static_elastic_run_completes(self, tmp_path):
        script = write_discovery(tmp_path, "echo localhost:2")
        env = make_env(tmp_path, steps=6, sleep=0.05)
        p = launch(script, env)
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) == 2, lines
        assert any("world 2" in ln for ln in lines)

    def test_graceful_scale_up(self, tmp_path):
        """Start at 2 procs; mid-run the discovery file grows to 3;
        workers resize without losing committed progress."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:2\n")
        script = write_discovery(tmp_path, f"cat {hosts_file}")
        env = make_env(tmp_path, steps=40, sleep=0.25)
        p = launch(script, env)
        try:
            time.sleep(8)  # let the 2-proc world make progress
            hosts_file.write_text("localhost:3\n")
            out, _ = p.communicate(timeout=420)
        finally:
            if p.poll() is None:
                p.kill()
                out = p.communicate()[0]
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert any("world 2" in ln for ln in lines), lines
        assert any("world 3" in ln for ln in lines), lines
        dones = [ln for ln in lines if "done" in ln]
        assert len(dones) == 3, (dones, out)
        # committed steps never regress below the resize point: the
        # max step logged in world 2 must be <= min step logged by the
        # new world's rank 0 continuation + 1
        w2 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 2" in ln]
        w3 = [int(ln.split()[1]) for ln in lines
              if ln.startswith("step") and "world 3" in ln]
        assert w2 and w3 and min(w3) >= max(w2) - 1, (max(w2), min(w3))

    def test_worker_failure_gang_restart(self, tmp_path):
        """Rank suicide mid-run: the driver restarts the gang and
        training completes (snapshot-level recovery)."""
        script = write_discovery(tmp_path, "echo localhost:2")
        env = make_env(tmp_path, steps=12, sleep=0.2)
        env["ELASTIC_TEST_DIE_AT"] = "4"  # rank 1 exits at step 4
        p = launch(script, env, extra=("--reset-limit", "3"))
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0, out
        lines = read_logs(tmp_path)
        assert sum("done" in ln for ln in lines) >= 2, (lines, out)
        # progress preservation: the rank died AFTER logging step 4 but
        # BEFORE committing it, so the snapshot holds step 3 and the
        # restarted gang must resume at step >= 4 — "step 1" may only
        # ever be logged by the first incarnation's 2 ranks.
        step1 = [ln for ln in lines if ln.startswith("step 1 ")]
        assert len(step1) <= 2, (step1, lines)


def test_jax_state_orbax_snapshot_roundtrip(tmp_path, hvd_single):
    """Orbax snapshot backend: async versioned commits, restart-style
    load (SURVEY.md §5.4 'integrate, don't rebuild')."""
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.elastic.state import JaxState
    path = str(tmp_path / "snap")
    st = JaxState(params={"w": jnp.arange(4.0)},
                  opt_state={"m": jnp.zeros(4)},
                  snapshot_path=path, snapshot_backend="orbax",
                  step=0, epoch=0)
    assert not st.maybe_load_snapshot()   # nothing yet; arms writes
    st.params = {"w": jnp.full(4, 7.0)}
    st.step = 3
    st.commit()
    st.params = {"w": jnp.full(4, 9.0)}   # uncommitted progress
    st.step = 4
    st.commit()
    # ensure async write landed before simulating the restart
    st._orbax().wait_until_finished()

    # "restarted gang": fresh state object, same path
    st2 = JaxState(params={"w": jnp.zeros(4)},
                   opt_state={"m": jnp.zeros(4)},
                   snapshot_path=path, snapshot_backend="orbax",
                   step=0, epoch=0)
    assert st2.maybe_load_snapshot()
    np.testing.assert_allclose(np.asarray(st2.params["w"]),
                               np.full(4, 9.0))
    assert st2.step == 4
    # restore() rolls back to the loaded commit
    st2.params = {"w": jnp.full(4, 1.0)}
    st2.restore()
    np.testing.assert_allclose(np.asarray(st2.params["w"]),
                               np.full(4, 9.0))


@pytest.mark.integration
def test_elastic_remote_spawn_via_ssh_shim(tmp_path):
    """Elastic driver's remote-spawn branch through the fake-ssh shim
    (see test_runner._write_fake_ssh): workers on 'fakehost' are
    spawned with the secret on stdin and the full (blocklist-filtered)
    env inlined; the job completes and the secret never rides argv."""
    import socket
    from tests.test_runner import _write_fake_ssh
    _, log = _write_fake_ssh(tmp_path)
    # The real hostname: not in LOCALHOSTS (so the ssh branch fires)
    # but resolvable, which elastic needs — rank 0 lives on the
    # "remote" host and every worker must reach its coordinator.
    host = socket.gethostname()
    script = write_discovery(tmp_path, f"echo {host}:2")
    env = make_env(tmp_path, steps=4, sleep=0.05)
    env["PATH"] = str(tmp_path) + os.pathsep + env["PATH"]
    p = launch(script, env)
    out, _ = p.communicate(timeout=420)
    assert p.returncode == 0, out
    lines = read_logs(tmp_path)
    assert sum("done" in ln for ln in lines) == 2, (lines, out)
    argv = log.read_text()
    assert "HOROVOD_SECRET=" not in argv
    assert "read -r __HVD_ENV" in argv
