"""hvd.flax conveniences: DistributedTrainState + sync_batch_stats
(reference analog: horovod/keras framework-native sugar). The real
2-proc broadcast/reduction phase lives in tests/mp_worker.py."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd


def test_train_state_converges_eager(hvd_single):
    """The 5-line flax experience trains a linear model to the exact
    solution through the distributed transformation."""
    key = jax.random.PRNGKey(0)
    w_true = jax.random.normal(key, (4, 1))
    X = jax.random.normal(jax.random.PRNGKey(1), (64, 4))
    Y = X @ w_true

    def apply_fn(variables, x):
        return x @ variables["params"]["w"]

    state = hvd.flax.DistributedTrainState.create(
        apply_fn=apply_fn, params={"w": jnp.zeros((4, 1))},
        tx=optax.sgd(0.1))

    def loss_fn(params):
        pred = state.apply_fn({"params": params}, X)
        return jnp.mean((pred - Y) ** 2)

    for _ in range(200):
        grads = jax.grad(loss_fn)(state.params)
        state = state.apply_gradients(grads=grads)
    assert float(loss_fn(state.params)) < 1e-6


def test_train_state_forwards_knobs(hvd_single):
    state = hvd.flax.DistributedTrainState.create(
        apply_fn=lambda v, x: x, params={"w": jnp.ones((2,))},
        tx=optax.sgd(1.0), compression=hvd.Compression.bf16,
        backward_passes_per_step=2)
    # k=2: first update accumulates (zero update), second applies.
    g = {"w": jnp.full((2,), 2.0)}
    state = state.apply_gradients(grads=g)
    np.testing.assert_allclose(np.asarray(state.params["w"]), 1.0)
    state = state.apply_gradients(grads=g)
    np.testing.assert_allclose(np.asarray(state.params["w"]), -1.0,
                               rtol=1e-2)  # bf16 wire


def test_sync_batch_stats_identity_at_size1(hvd_single):
    stats = {"bn": {"mean": jnp.arange(3.0), "var": jnp.ones(3)}}
    out = hvd.flax.sync_batch_stats(stats)
    np.testing.assert_allclose(np.asarray(out["bn"]["mean"]),
                               np.arange(3.0))
    assert hvd.flax.sync_batch_stats({}) == {}
