"""Worker for the 2-rank distributed-tracing integration test: runs
named negotiated allreduces with HOROVOD_TIMELINE set (every rank
writes a per-rank trace on a monotonic anchor, rank 1's dispatches
are slowed by an injected dispatch.entry delay), then asserts its own
per-rank trace file exists. The test process merges the files
afterwards and checks the straggler report names rank 1."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import tracing  # noqa: E402
from horovod_tpu.timeline import Timeline  # noqa: E402


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n

    for step in range(6):
        tracing.set_step(step)
        out = hvd.allreduce(jnp.ones(256, jnp.float32), op=hvd.Sum,
                            name=f"grads_{step}")
        np.testing.assert_allclose(np.asarray(out), float(n))
    hvd.barrier()

    # Every rank records: rank 0 at the configured path, rank 1 at
    # the .rank1 sibling the merge step discovers.
    path = Timeline.rank_path(os.environ["HOROVOD_TIMELINE"], r)
    assert os.path.exists(path), path

    # The runtime skew histogram saw the same lateness the offline
    # report attributes: the NON-delayed rank (rank 0) arrives early
    # and waits, so its own lateness stays small; the delayed rank
    # observes its arrival delta behind rank 0.
    digest = tracing.trace_digest()
    assert digest["spans"].get("submit", {}).get("count", 0) >= 6
    hvd.shutdown()
    print("TRACING WORKER OK", flush=True)


main()
