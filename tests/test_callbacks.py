"""Callback layer tests (reference: horovod/_keras/callbacks.py —
BroadcastGlobalVariablesCallback / MetricAverageCallback /
LearningRateWarmupCallback / LearningRateScheduleCallback; the BERT
BASELINE config drives these)."""

import subprocess
import sys
import os

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.callbacks import (BroadcastParametersCallback,
                                   CallbackContext, CallbackList,
                                   LearningRateScheduleCallback,
                                   LearningRateWarmupCallback,
                                   MetricAverageCallback,
                                   lr_scale_schedule,
                                   multiplier_schedule,
                                   warmup_schedule)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLRCallbacks:
    def test_warmup_ramp(self):
        ctx = CallbackContext()
        cb = LearningRateWarmupCallback(warmup_epochs=4,
                                        target_scale=8.0)
        scales = []
        for e in range(6):
            cb.on_epoch_begin(e, ctx)
            scales.append(ctx.lr_scale)
        # linear ramp 1 -> 8 over 4 epochs, then flat at 8
        np.testing.assert_allclose(scales,
                                   [2.75, 4.5, 6.25, 8.0, 8.0, 8.0])

    def test_warmup_defaults_to_size(self, hvd_single):
        ctx = CallbackContext()
        cb = LearningRateWarmupCallback(warmup_epochs=1)
        cb.on_epoch_begin(0, ctx)
        assert ctx.lr_scale == float(hvd_single.size())

    def test_schedule_staircase_window(self):
        ctx = CallbackContext()
        warm = LearningRateWarmupCallback(warmup_epochs=1,
                                          target_scale=4.0)
        decay = LearningRateScheduleCallback(0.5, start_epoch=2)
        cbs = CallbackList([warm, decay])
        seen = []
        for e in range(4):
            cbs.on_epoch_begin(e, ctx)
            seen.append(ctx.lr_scale)
        # warmup sets scale to 4 every epoch; decay multiplies after it
        np.testing.assert_allclose(seen, [4.0, 4.0, 2.0, 2.0])

    def test_schedule_callable_multiplier(self):
        ctx = CallbackContext()
        cb = LearningRateScheduleCallback(lambda e: 0.1 ** e,
                                          start_epoch=1, end_epoch=3)
        for e in range(4):
            ctx.lr_scale = 1.0
            cb.on_epoch_begin(e, ctx)
            want = 0.1 ** e if 1 <= e < 3 else 1.0
            assert ctx.lr_scale == pytest.approx(want)

    def test_lr_scale_schedule_reads_live(self):
        ctx = CallbackContext()
        sched = lr_scale_schedule(ctx, 0.01)
        assert float(sched(0)) == pytest.approx(0.01)
        ctx.lr_scale = 8.0
        assert float(sched(123)) == pytest.approx(0.08)


class TestOptaxSchedules:
    def test_warmup_schedule_pure(self):
        s = warmup_schedule(0.1, warmup_steps=10, target_scale=4.0)
        assert float(s(0)) == pytest.approx(0.1 * (1 + 3 * 0.1))
        assert float(s(9)) == pytest.approx(0.4)
        assert float(s(100)) == pytest.approx(0.4)

    def test_warmup_schedule_with_after(self):
        after = lambda step: 0.4 * 0.5 ** (step // 10)  # noqa: E731
        s = warmup_schedule(0.1, warmup_steps=10, target_scale=4.0,
                            after=after)
        assert float(s(9)) == pytest.approx(0.4)
        assert float(s(10)) == pytest.approx(0.4)
        assert float(s(20)) == pytest.approx(0.2)

    def test_multiplier_schedule(self):
        s = multiplier_schedule(1.0, [(10, 0.1), (20, 0.1)])
        assert float(s(0)) == pytest.approx(1.0)
        assert float(s(10)) == pytest.approx(0.1)
        assert float(s(25)) == pytest.approx(0.01)

    def test_composes_with_optax(self, hvd_single):
        import optax
        opt = optax.adamw(warmup_schedule(1e-3, 5, target_scale=2.0))
        params = {"w": jnp.ones(3)}
        st = opt.init(params)
        up, st = opt.update({"w": jnp.ones(3)}, st, params)
        assert jnp.all(jnp.isfinite(up["w"]))


class TestBroadcastAndMetrics:
    def test_broadcast_callback_single(self, hvd_single):
        ctx = CallbackContext(params={"w": jnp.arange(4.0)},
                              opt_state={"m": jnp.zeros(4)})
        BroadcastParametersCallback().on_train_begin(ctx)
        np.testing.assert_allclose(np.asarray(ctx.params["w"]),
                                   np.arange(4.0))

    def test_metric_average_single(self, hvd_single):
        cb = MetricAverageCallback()
        out = cb.on_epoch_end(0, {"loss": 2.5, "tag": "x"},
                              CallbackContext())
        assert out["loss"] == pytest.approx(2.5)
        assert out["tag"] == "x"


@pytest.mark.integration
def test_bert_example_with_callbacks(multiproc_data_plane):
    """BASELINE config 3 driver: the BERT example runs 2-process with
    warmup + broadcast + metric averaging through the callback API.
    (multiproc_data_plane: the on_train_begin parameter broadcast is
    a cross-process XLA collective, absent on this image's jaxlib —
    the failure mode is the data plane, not the example or the
    callbacks, so it shares the one probe-gated skip.)"""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         sys.executable, os.path.join("examples",
                                      "bert_large_pretraining.py"),
         "--epochs", "2", "--steps", "2", "--batch-size", "2",
         "--seq-len", "16", "--warmup-epochs", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "lr_scale=2.00" in r.stdout, r.stdout
    assert "avg loss" in r.stdout
