"""Driver/task service layer: signed RPC wire, NIC enumeration and
probing, registration, coordinator election, and the probed launch
path end-to-end on localhost.

Reference test analog: test/single/test_service.py (driver/task RPC)
and test_run.py's driver-flow coverage in the reference suite.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.runner import network
from horovod_tpu.runner import secret as _secret
from horovod_tpu.runner.driver_service import DriverService
from horovod_tpu.runner.service import (BasicClient, BasicService,
                                        WireError, recv_frame,
                                        send_frame)
from horovod_tpu.runner.task_service import TaskService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestWire:
    def test_roundtrip(self):
        a, b = socket.socketpair()
        send_frame(a, "key", {"x": [1, 2, 3]})
        assert recv_frame(b, "key") == {"x": [1, 2, 3]}
        a.close(); b.close()

    def test_bad_secret_rejected(self):
        a, b = socket.socketpair()
        send_frame(a, "key1", {"x": 1})
        with pytest.raises(WireError):
            recv_frame(b, "key2")
        a.close(); b.close()


class TestBasicService:
    def test_dispatch_and_denial(self):
        svc = BasicService("t", "sekrit")
        svc.handle("echo", lambda req, peer: {"got": req["v"]})
        try:
            ok = BasicClient("127.0.0.1", svc.port, "sekrit")
            assert ok.request({"type": "echo", "v": 7}) == {"got": 7}
            bad = BasicClient("127.0.0.1", svc.port, "wrong")
            with pytest.raises(WireError):
                bad.request({"type": "echo", "v": 7})
            assert ok.request({"type": "nope"})["error"].startswith(
                "unknown")
        finally:
            svc.close()


class TestNetwork:
    def test_local_addresses_shape(self):
        addrs = network.local_addresses()
        assert isinstance(addrs, dict)
        for iface, ips in addrs.items():
            assert isinstance(iface, str) and isinstance(ips, list)
            assert all(not ip.startswith("127.") for ip in ips)

    def test_probe(self):
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        try:
            assert network.probe("127.0.0.1", port, timeout=2.0)
        finally:
            lst.close()
        assert not network.probe("127.0.0.1", port, timeout=0.5)


class TestDriverTaskFlow:
    """In-process driver + two task services over loopback — the
    registration → probe → election → run → exit-collection flow."""

    def _mk(self, n_hosts=2):
        sec = _secret.make_secret()
        driver = DriverService(sec, num_hosts=n_hosts)
        tasks = []
        for hid in ["hostA", "hostB"][:n_hosts]:
            t = TaskService(hid, [("127.0.0.1", driver.port)], sec)
            t.register(timeout=10.0)
            tasks.append(t)
        return sec, driver, tasks

    def test_register_probe_elect(self):
        sec, driver, tasks = self._mk()
        try:
            driver.wait_for_registration(timeout=10.0)
            assert set(driver.tasks) == {"hostA", "hostB"}
            driver.probe()
            for rec in driver.tasks.values():
                assert rec.routable, "loopback must be routable"
            coord = driver.elect_coordinator("hostA")
            assert coord in driver.tasks["hostA"].candidates()
        finally:
            for t in tasks:
                t.service.close()
            driver.close()

    def test_registration_timeout_lists_missing(self):
        sec = _secret.make_secret()
        driver = DriverService(sec, num_hosts=2)
        try:
            with pytest.raises(TimeoutError, match="2 task"):
                driver.wait_for_registration(timeout=0.2)
        finally:
            driver.close()

    def test_unauthenticated_register_rejected(self):
        sec, driver, tasks = self._mk(n_hosts=1)
        try:
            evil = BasicClient("127.0.0.1", driver.port, "not-the-key")
            with pytest.raises(WireError):
                evil.request({"type": "register", "host_id": "mallory",
                              "port": 1, "addrs": {}})
            assert "mallory" not in driver.tasks
        finally:
            for t in tasks:
                t.service.close()
            driver.close()

    def test_run_and_exit_collection(self, tmp_path):
        sec, driver, tasks = self._mk()
        try:
            driver.wait_for_registration(timeout=10.0)
            driver.probe()
            out = tmp_path / "out"
            code = ("import os,sys;"
                    "open(os.environ['OUTF']+os.environ['HOROVOD_RANK'],"
                    "'w').write(os.environ['HOROVOD_RANK']);"
                    "sys.exit(int(os.environ['HOROVOD_RANK']) * 0)")
            by_host = {
                "hostA": [(_FakeInfo(0), {"HOROVOD_RANK": "0",
                                          "OUTF": str(out)})],
                "hostB": [(_FakeInfo(1), {"HOROVOD_RANK": "1",
                                          "OUTF": str(out)})],
            }
            driver.run_ranks([sys.executable, "-c", code], REPO, by_host)
            assert driver.wait(num_ranks=2) == 0
            assert (tmp_path / "out0").read_text() == "0"
            assert (tmp_path / "out1").read_text() == "1"
        finally:
            for t in tasks:
                t.service.close()
            driver.close()

    def test_failing_rank_propagates(self):
        sec, driver, tasks = self._mk()
        try:
            driver.wait_for_registration(timeout=10.0)
            driver.probe()
            code = ("import os,sys;"
                    "sys.exit(3 if os.environ['HOROVOD_RANK']=='1' "
                    "else 0)")
            by_host = {
                "hostA": [(_FakeInfo(0), {"HOROVOD_RANK": "0"})],
                "hostB": [(_FakeInfo(1), {"HOROVOD_RANK": "1"})],
            }
            driver.run_ranks([sys.executable, "-c", code], REPO, by_host)
            assert driver.wait(num_ranks=2) == 3
        finally:
            for t in tasks:
                t.service.close()
            driver.close()


class _FakeInfo:
    def __init__(self, rank):
        self.rank = rank


@pytest.mark.integration
class TestProbedLaunch:
    def test_run_with_driver_localhost(self, capfd):
        """End-to-end probed launch: task service spawned as a real
        subprocess, registration over loopback, ranks launched through
        it, output prefixed, exit codes collected."""
        from horovod_tpu.runner import launch
        env = {k: v for k, v in os.environ.items()}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import os; print('RANK', os.environ['HOROVOD_RANK'], "
                "'IFACE', os.environ.get('HOROVOD_IFACE', '-'))")
        old = dict(os.environ)
        os.environ["PYTHONPATH"] = env["PYTHONPATH"]
        try:
            rc = launch.run_with_driver(
                [sys.executable, "-c", code], np_=2,
                start_timeout=60.0)
        finally:
            os.environ.clear()
            os.environ.update(old)
        assert rc == 0
        out = capfd.readouterr().out
        assert "RANK 0" in out and "RANK 1" in out


class TestNicRestriction:
    def test_candidates_filtered_by_interface(self):
        from horovod_tpu.runner.driver_service import TaskRecord
        addrs = {"eth0": ["10.0.0.5"], "docker0": ["172.17.0.1"]}
        # unrestricted: registration source first, then all NICs
        rec = TaskRecord("h", "10.0.0.5", 1234, addrs)
        assert rec.candidates() == ["10.0.0.5", "172.17.0.1"]
        # restricted to eth0: docker0 dropped; source kept (it IS
        # eth0's address)
        rec = TaskRecord("h", "10.0.0.5", 1234, addrs, ifaces=["eth0"])
        assert rec.candidates() == ["10.0.0.5"]
        # source NOT on an allowed NIC: dropped too
        rec = TaskRecord("h", "172.17.0.1", 1234, addrs,
                         ifaces=["eth0"])
        assert rec.candidates() == ["10.0.0.5"]

    def test_parser_accepts_network_interfaces(self):
        from horovod_tpu.runner.launch import make_parser
        args = make_parser().parse_args(
            ["-np", "2", "--driver", "--network-interfaces",
             "eth0,ens5", "python", "t.py"])
        assert args.network_interfaces == "eth0,ens5"

    def test_bad_interface_name_gives_actionable_error(self):
        from horovod_tpu.runner.driver_service import (DriverService,
                                                       TaskRecord)
        sec = _secret.make_secret()
        driver = DriverService(sec, num_hosts=1, ifaces=["eht0"])
        try:
            driver.tasks["h"] = TaskRecord(
                "h", "10.0.0.5", 1, {"eth0": ["10.0.0.5"]},
                ifaces=["eht0"])
            with pytest.raises(RuntimeError,
                               match="network-interfaces"):
                driver.probe(timeout=0.1)
        finally:
            driver.close()
