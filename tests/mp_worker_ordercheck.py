"""2-proc worker for the execution-order assertion: ranks submit the
same ops in OPPOSITE program order; the negotiated controller must
still deliver one agreed sequence, so check_execution_order passes.
Launched by test_order_check.py via the real launcher."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    os.environ["HOROVOD_ORDER_CHECK"] = "1"
    hvd.init()
    r = hvd.rank()
    names = [f"t{i}" for i in range(8)]
    order = names if r == 0 else list(reversed(names))
    handles = [hvd.allreduce_async(jnp.full(4, float(r)), name=n)
               for n in order]
    for h in handles:
        hvd.synchronize(h)
    n = hvd.check_execution_order()
    assert n >= len(names), n
    # a second round reusing the same names (response-cache path);
    # async like round 1 — SYNCHRONOUS submission in opposite orders
    # would deadlock by design (each rank blocks on a tensor the
    # other hasn't announced; the stall inspector's territory).
    handles = [hvd.allreduce_async(jnp.ones(4), name=nm)
               for nm in order]
    for h in handles:
        hvd.synchronize(h)
    hvd.check_execution_order()
    print(f"rank {r}: ORDER CHECK OK ({n} ops at first check)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
