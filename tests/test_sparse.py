"""Sparse allreduce (BCOO) — the reference's sparse-gradient path
(reference: horovod/torch/mpi_ops.py sparse_allreduce_async;
horovod/torch/optimizer.py sparse_as_dense). Single-process semantics
here; the real 2/4-proc phase lives in tests/mp_worker.py."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.experimental import sparse as jsparse

import horovod_tpu as hvd


@pytest.fixture()
def hvd_init():
    hvd.init()
    yield
    hvd.shutdown()


def _bcoo_with_duplicates():
    # Embedding-row shaped gradient: rows 1 and 4 touched, row 1 twice
    # (the duplicate-coalescing case the torch sparse path hits when a
    # token repeats in a batch).
    idx = jnp.array([[1], [4], [1]])
    data = jnp.arange(9, dtype=jnp.float32).reshape(3, 3)
    b = jsparse.BCOO((data, idx), shape=(6, 3))
    dense = np.zeros((6, 3), np.float32)
    dense[1] = np.asarray(data[0] + data[2])
    dense[4] = np.asarray(data[1])
    return b, dense


def test_sparse_allreduce_coalesces_duplicates(hvd_init):
    b, dense = _bcoo_with_duplicates()
    out = hvd.sparse_allreduce(b, op=hvd.Sum, name="sp.sum")
    assert isinstance(out, jsparse.BCOO)
    assert out.nse == 2  # duplicates summed, not concatenated
    np.testing.assert_allclose(np.asarray(out.todense()), dense)


def test_sparse_allreduce_handle_protocol(hvd_init):
    b, dense = _bcoo_with_duplicates()
    h = hvd.sparse_allreduce_async(b, name="sp.h")
    assert isinstance(h, hvd.SparseAllreduceHandle)
    out = hvd.synchronize(h)  # duck-typed through the top-level API
    assert hvd.poll(h)
    # Average at world size 1 == Sum.
    np.testing.assert_allclose(np.asarray(out.todense()), dense)
    # Synchronizing twice returns the cached result.
    assert hvd.synchronize(h) is out


def test_sparse_allreduce_empty_nnz(hvd_init):
    e = jsparse.BCOO((jnp.zeros((0, 3)), jnp.zeros((0, 1), jnp.int32)),
                     shape=(6, 3))
    out = hvd.sparse_allreduce(e)
    np.testing.assert_allclose(np.asarray(out.todense()),
                               np.zeros((6, 3)))


def test_sparse_allreduce_rejects_int_average(hvd_init):
    """Same integer/Average restriction as the dense op — otherwise
    the result dtype would depend on world size."""
    b = jsparse.BCOO((jnp.array([3, 5], jnp.int32),
                      jnp.array([[0], [2]], jnp.int32)), shape=(4,))
    with pytest.raises(ValueError, match="[Aa]verage"):
        hvd.sparse_allreduce(b)   # default op is Average
    out = hvd.sparse_allreduce(b, op=hvd.Sum, name="sp.int")
    assert out.data.dtype == jnp.int32


def test_sparse_allreduce_rejects_adasum_and_dense(hvd_init):
    b, _ = _bcoo_with_duplicates()
    with pytest.raises(NotImplementedError):
        hvd.sparse_allreduce(b, op=hvd.Adasum)
    with pytest.raises(TypeError):
        hvd.sparse_allreduce(jnp.ones((3, 3)))


def test_optimizer_sparse_eager_path(hvd_init):
    """BCOO gradient leaves ride sparse_allreduce; the reduced update
    is dense (optax inner transforms are dense-only — documented
    divergence from torch's sparse-aware SGD)."""
    b, dense = _bcoo_with_duplicates()
    params = {"emb": jnp.ones((6, 3)), "w": jnp.ones((2,))}
    grads = {"emb": b, "w": jnp.full((2,), 2.0)}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    upd, _ = opt.update(grads, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["emb"]), -dense)
    np.testing.assert_allclose(np.asarray(upd["w"]), -2.0)


def test_optimizer_sparse_as_dense(hvd_init):
    b, dense = _bcoo_with_duplicates()
    params = {"emb": jnp.ones((6, 3))}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), sparse_as_dense=True)
    upd, _ = opt.update({"emb": b}, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["emb"]), -dense)


def test_optimizer_sparse_predivide_matches_average(hvd_init):
    b, dense = _bcoo_with_duplicates()
    params = {"emb": jnp.ones((6, 3))}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   gradient_predivide_factor=2.0)
    upd, _ = opt.update({"emb": b}, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["emb"]), -dense,
                               rtol=1e-6)


def test_optimizer_groups_remap_around_sparse_leaf(hvd_init):
    """Explicit fusion groups name FULL-tree leaf indices; with a BCOO
    leaf in the middle, the dense indices must remap onto the
    compacted dense list (leaf 1 sparse, group [0, 2] must still fuse
    leaves 0 and 2, not crash out-of-range)."""
    b, dense = _bcoo_with_duplicates()
    params = {"a": jnp.ones((2,)), "emb": jnp.ones((6, 3)),
              "z": jnp.ones((3,))}
    grads = {"a": jnp.full((2,), 2.0), "emb": b,
             "z": jnp.full((3,), 3.0)}
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), groups=[[0, 2]])
    upd, _ = opt.update(grads, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["a"]), -2.0)
    np.testing.assert_allclose(np.asarray(upd["emb"]), -dense)
    np.testing.assert_allclose(np.asarray(upd["z"]), -3.0)
    # A group naming the sparse leaf is rejected with guidance.
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), groups=[[1]])
    with pytest.raises(ValueError, match="sparse_allreduce"):
        opt.update(grads, opt.init(params), params)
    # Out-of-range indices still error against the FULL tree size.
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), groups=[[0, 5]])
    with pytest.raises(ValueError, match="out of range"):
        opt.update(grads, opt.init(params), params)


def test_sparse_handle_error_is_sticky(hvd_init):
    """After a sub-collective failure the composite handle re-raises
    the ORIGINAL error on retry (never a bare KeyError from the
    released engine handle), and poll() reports done."""
    b, _ = _bcoo_with_duplicates()
    h = hvd.sparse_allreduce_async(b, name="sp.err")
    err = RuntimeError("injected wire failure")
    h._error = err  # simulate a failed values batch after idx release
    assert hvd.poll(h)
    with pytest.raises(RuntimeError, match="injected wire failure"):
        hvd.synchronize(h)


def test_optimizer_sparse_restrictions(hvd_init):
    b, _ = _bcoo_with_duplicates()
    params = {"emb": jnp.ones((6, 3))}
    grads = {"emb": b}
    # Local aggregation needs a dense accumulator.
    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   backward_passes_per_step=2)
    with pytest.raises(ValueError, match="sparse_as_dense"):
        opt.update(grads, opt.init(params), params)
    # The in-jit axis path is dense-only.
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), axis_name="data")
    with pytest.raises(ValueError, match="sparse_as_dense"):
        opt.update(grads, opt.init(params), params)
    # Adasum sparse names the escape hatch.
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Adasum)
    with pytest.raises(NotImplementedError, match="sparse_as_dense"):
        opt.update(grads, opt.init(params), params)
