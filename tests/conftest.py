"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed semantics
without a cluster (SURVEY.md §4): the reference runs Gloo over
loopback; here multi-*device* semantics run on
--xla_force_host_platform_device_count=8 CPU devices, and
multi-*process* semantics run by spawning real subprocesses via the
launcher (see test_multiprocess.py), each on its own CPU backend.
"""

import os
import sys

# Must happen before jax import anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
# Neutralize the axon TPU sitecustomize hook (it force-registers the
# TPU backend even when JAX_PLATFORMS=cpu).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Exercise float64/int64 paths like the reference CPU tests do.
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


@pytest.fixture
def hvd_single():
    """hvd initialized in single-process mode; shut down after."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


_NO_MULTIPROC = ("this jaxlib's CPU backend cannot run cross-process "
                 "collectives (affects every multiprocess data-plane "
                 "integration test; the control plane — negotiation, "
                 "timelines, launchers — still runs and stays tested)")
_multiproc_probe_result = None


@pytest.fixture(scope="session")
def multiproc_data_plane():
    """Session-scoped capability probe for the cross-process DATA
    plane: one tiny 2-rank allreduce through the real launcher. On
    jaxlibs whose CPU backend cannot run multiprocess computations
    (this CI image), every data-plane mp test skips here with one
    shared reason instead of each failing identically — the same gate
    test_chaos.py/test_numerics.py apply module-locally, hoisted so
    the controller/runner/span/callbacks mp tests share one probe
    (and one subprocess) per session."""
    global _multiproc_probe_result
    if _multiproc_probe_result is None:
        import subprocess
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, "-c",
             "import jax.numpy as jnp; import horovod_tpu as hvd; "
             "hvd.init(); hvd.allreduce(jnp.ones(4), name='probe'); "
             "hvd.shutdown()"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=180)
        out = r.stdout + r.stderr
        if "Multiprocess computations aren't implemented" in out:
            _multiproc_probe_result = "incapable"
        else:
            assert r.returncode == 0, out
            _multiproc_probe_result = "ok"
    if _multiproc_probe_result == "incapable":
        pytest.skip(_NO_MULTIPROC)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from jax.sharding import Mesh
    import numpy as np
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("proc",))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "integration: spawns real subprocesses")
    config.addinivalue_line(
        "markers",
        "slow: long randomized soaks, excluded from tier-1 "
        "(`pytest -m 'not slow'`); the fast fixed-seed chaos tests "
        "stay in tier-1 so the fault seams cannot silently rot")
    config.addinivalue_line(
        "markers",
        "smoke: fast cross-subsystem tier (`pytest -m smoke`, ~2-3 "
        "min on the 1-core CI host) — one or two representatives per "
        "subsystem, for drivers that cannot afford the full suite")
    config.addinivalue_line(
        "markers",
        "nightly: heavy multi-process stress/soak tests (minutes "
        "each — subprocess gangs, C++ scale binaries, compile-heavy "
        "matrices). Implies `slow` (see "
        "pytest_collection_modifyitems), so tier-1's "
        "`-m 'not slow'` excludes them and the suite stays inside "
        "its 870 s cap; run `pytest -m nightly` on the long lane. "
        "Cheap fixed-seed chaos/integration representatives stay in "
        "tier-1 so the multiprocess seams cannot silently rot")


# One or two fast representatives per subsystem (round-4 verdict weak
# #6: the full suite is ~20 min on a 1-core host; tooling needs a
# smoke tier). Curated here rather than decorating each file so the
# tier stays visible and editable in one place. Node-id bases
# (parametrized variants inherit the mark).
_SMOKE = {
    # basics / config / process sets
    "tests/test_basics.py::test_init_rank_size",
    "tests/test_basics.py::test_shutdown_and_reinit",
    "tests/test_basics.py::test_config_env_parsing",
    "tests/test_basics.py::test_process_set_registration",
    # eager collective API (single-process semantics)
    "tests/test_collectives_single.py::test_allreduce_scaling",
    "tests/test_collectives_single.py::test_grouped_allreduce",
    "tests/test_collectives_single.py::test_alltoall_single",
    "tests/test_collectives_single.py::test_reducescatter_single",
    # controller (python core + native-core unit)
    "tests/test_controller.py::TestControllerSingleProcess::"
    "test_allreduce_roundtrip",
    "tests/test_controller.py::TestControllerSingleProcess::"
    "test_compression_roundtrip",
    "tests/test_controller.py::TestNativeCoreUnit::"
    "test_fusion_packs_same_key",
    # control-plane auth
    "tests/test_control_plane_auth.py::"
    "test_wrong_mac_rejected_and_slot_stays_free",
    # data-plane kernels (flat, fused, hier-wide HLO, adasum)
    "tests/test_dispatch_kernels.py::test_fused_group_allreduce",
    "tests/test_dispatch_kernels.py::test_allgather_uneven",
    "tests/test_dispatch_kernels.py::test_alltoall_kernel",
    "tests/test_dispatch_kernels.py::TestHierWide::"
    "test_dcn_phase_moves_fraction",
    "tests/test_dispatch_kernels.py::TestAdasumVHDD::"
    "test_non_pow2_matches_oracle",
    # launcher / hosts / ssh
    "tests/test_runner.py::TestHosts::test_parse",
    "tests/test_runner.py::TestEnvAndSsh::test_build_env",
    "tests/test_span_devices.py::TestPerChipLaunchEnv::"
    "test_single_host_four_chips",
    # driver/task rendezvous services
    "tests/test_driver_service.py::TestDriverTaskFlow::"
    "test_register_probe_elect",
    # elastic driver + checkpoint state
    "tests/test_elastic.py::TestElastic::test_unit_driver_pieces",
    "tests/test_elastic.py::test_jax_state_orbax_snapshot_roundtrip",
    # order check (race detection) unit
    "tests/test_order_check.py::TestOrderCheckUnit::"
    "test_digest_detects_divergence",
    # pallas kernels
    "tests/test_pallas_kernels.py::test_pair_combine_matches_numpy",
    # parallel strategies (mesh, ring attention, tp/fsdp oracle)
    "tests/test_parallel.py::TestMeshSpec::test_build_mesh_axes",
    "tests/test_parallel.py::TestRingAttention::test_matches_full",
    "tests/test_transformer.py::TestShardedLossMatchesOracle::"
    "test_moe_ep",
    "tests/test_transformer.py::TestFSDP::"
    "test_fsdp_x_tp_explicit_path",
    # models
    "tests/test_vgg.py::test_vgg16_param_count_and_forward",
    "tests/test_inception.py::test_inception_v3_param_count_and_forward",
    # sparse allreduce (BCOO)
    "tests/test_sparse.py::test_sparse_allreduce_coalesces_duplicates",
    # torch frontend binding
    "tests/test_torch_frontend.py::TestTensorOps::"
    "test_allreduce_dtype_preserved",
    # flax frontend sugar
    "tests/test_flax_frontend.py::test_train_state_converges_eager",
    # grouped allgather/reducescatter composite handles
    "tests/test_collectives_single.py::test_grouped_allgather_single",
    # sync batch norm
    "tests/test_sync_batch_norm.py::test_sync_bn_matches_global_batch",
    # metrics registry + stall gauges (observability subsystem)
    "tests/test_metrics.py::TestRegistry::test_prometheus_golden",
    "tests/test_metrics.py::test_stall_gauge_rises_and_clears",
    # timeline + autotune
    "tests/test_timeline_autotune.py::TestTimeline::"
    "test_valid_chrome_trace",
    "tests/test_timeline_autotune.py::TestAutotuner::"
    "test_wired_through_controller",
    # callbacks
    "tests/test_callbacks.py::TestLRCallbacks::test_warmup_ramp",
    # one real multi-process integration path (eager wide data plane
    # over the C++ controller) — the flagship product surface; only
    # the cheapest parametrization (exact node id, with brackets).
    "tests/test_span_devices.py::test_eager_span_devices[2-2]",
}


# Heavy multi-process stress/soak tests for the nightly lane (round-6
# satellite; VERDICT r05 weak 5-6: suite wall hit 40:25 and compounds
# ~+10 min/round, blowing tier-1's 870 s cap). Measured on this host
# (pytest --durations, 2-core CI image): the elastic scale matrix
# alone burns ~85 min (multi-minute discovery/rendezvous cycles per
# resize), the two-proc example matrix ~2.5 min, the C++ scale/TSAN
# stress binaries ~2 min, the wide-span 3/8-proc variants ~1 min.
# Curated here like _SMOKE so the tier stays visible in one place:
# base node ids (parametrized variants inherit) or exact ids with
# brackets for single parametrizations. One cheap representative per
# subsystem stays in tier-1 (unit/driver pieces, 2-proc launch,
# span[2-2], fixed-seed chaos), so no multiprocess seam goes
# unwatched between nightly runs.
_NIGHTLY = {
    # elastic resize/churn matrix: real drivers, discovery polling,
    # multi-minute rendezvous cycles per membership change
    "tests/test_elastic.py::TestElastic::test_static_elastic_run_completes",
    "tests/test_elastic.py::TestElastic::test_graceful_scale_up",
    "tests/test_elastic.py::TestElastic::test_graceful_scale_down",
    "tests/test_elastic.py::TestElastic::test_scale_down_then_up_churn",
    "tests/test_elastic.py::TestElastic::"
    "test_scale_down_below_min_np_is_ignored",
    "tests/test_elastic.py::TestElastic::test_resize_rebuilds_wide_mesh",
    "tests/test_elastic.py::TestElastic::"
    "test_torch_frontend_elastic_scale_up",
    "tests/test_elastic.py::TestElastic::test_worker_failure_gang_restart",
    "tests/test_elastic.py::test_elastic_remote_spawn_via_ssh_shim",
    # multi-process example matrix (launcher gangs on shared cores)
    "tests/test_examples.py::TestExamples::test_elastic_resnet",
    "tests/test_examples.py::TestExamples::test_mnist_two_proc",
    "tests/test_examples.py::TestExamples::test_flax_train_state_two_proc",
    "tests/test_examples.py::TestExamples::test_torch_mnist_two_proc",
    "tests/test_examples.py::TestExamples::test_pipelined_two_proc",
    "tests/test_examples.py::TestExamples::test_bert_fp16_fusion",
    "tests/test_examples.py::TestExamples::test_llama_adasum",
    # C++ control-plane scale/TSAN stress binaries
    "tests/test_scale_stress.py::test_control_plane_scales_to_64_workers",
    "tests/test_scale_stress.py::test_slow_worker_does_not_stall_healthy_ranks",
    # flat-vs-tree A/B at 256 simulated ranks (two 256-rank gangs;
    # the cheap tree representatives — tree_unit, 16-rank tree row,
    # 4-proc wiring — stay in tier-1)
    "tests/test_scale_stress.py::test_flat_vs_tree_256_root_work",
    "tests/test_tsan_stress.py::test_controller_stress_under_tsan",
    # wide-span multi-proc variants beyond the 2-proc representative
    "tests/test_span_devices.py::test_eager_span_devices[3-2]",
    "tests/test_span_devices.py::test_eager_span_devices[8-2]",
    "tests/test_span_devices.py::test_hierarchical_composes_with_devices",
    # 4-proc variants of tests whose 2-proc twin stays in tier-1
    "tests/test_controller.py::TestNegotiationMultiProcess::"
    "test_negotiation[4]",
    "tests/test_runner.py::TestRealLaunch::test_two_process_collectives[4]",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.nodeid.split("[")[0] in _SMOKE
                or item.nodeid in _SMOKE):
            item.add_marker(pytest.mark.smoke)
        if (item.nodeid in _NIGHTLY
                or item.nodeid.split("[")[0] in _NIGHTLY):
            item.add_marker(pytest.mark.nightly)
        # nightly extends the slow scheme: one decorator (or a
        # _NIGHTLY entry) both names the long lane (`pytest -m
        # nightly`) and keeps tier-1's `-m 'not slow'` filter
        # excluding the test without editing the tier-1 command.
        if item.get_closest_marker("nightly") is not None:
            item.add_marker(pytest.mark.slow)
