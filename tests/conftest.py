"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed semantics
without a cluster (SURVEY.md §4): the reference runs Gloo over
loopback; here multi-*device* semantics run on
--xla_force_host_platform_device_count=8 CPU devices, and
multi-*process* semantics run by spawning real subprocesses via the
launcher (see test_multiprocess.py), each on its own CPU backend.
"""

import os
import sys

# Must happen before jax import anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
# Neutralize the axon TPU sitecustomize hook (it force-registers the
# TPU backend even when JAX_PLATFORMS=cpu).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Exercise float64/int64 paths like the reference CPU tests do.
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


@pytest.fixture
def hvd_single():
    """hvd initialized in single-process mode; shut down after."""
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture(scope="session")
def eight_device_mesh():
    from jax.sharding import Mesh
    import numpy as np
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("proc",))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "integration: spawns real subprocesses")
