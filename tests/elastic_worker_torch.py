"""Elastic training worker on the TORCH frontend: a toy torch
training loop under hvd.elastic.run with TorchState (reference:
test/integration elastic torch scripts), logging
(step, world) progress per rank and surviving membership changes via
commit/restore/sync over the shared elastic machinery."""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402

LOG = os.environ["ELASTIC_TEST_LOG"]
TOTAL_STEPS = int(os.environ.get("ELASTIC_TEST_STEPS", "20"))
STEP_SLEEP = float(os.environ.get("ELASTIC_TEST_SLEEP", "0.2"))


def log_line(msg):
    with open(f"{LOG}.{os.environ.get('HOROVOD_RANK', '?')}", "a") as f:
        f.write(msg + "\n")


def main():
    hvd.init()
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    state = hvd.elastic.TorchState(model, opt, step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < TOTAL_STEPS:
            x = torch.randn(8, 2)
            y = torch.zeros(8, 1)
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(state.model(x), y)
            loss.backward()
            opt.step()
            state.step += 1
            state.commit()
            log_line(f"step {state.step} world {hvd.size()} "
                     f"rank {hvd.rank()} loss {float(loss.detach()):.4f}")
            time.sleep(STEP_SLEEP)

    train(state)
    # weights must agree across ranks at the end (the elastic loop
    # syncs on every membership change; training itself reduces
    # gradients) — allgather and compare on rank 0.
    w = hvd.allgather(state.model.weight.detach().reshape(1, -1),
                      name="final_w")
    if hvd.rank() == 0:
        import numpy as np
        for i in range(1, hvd.size()):
            np.testing.assert_allclose(w[i].numpy(), w[0].numpy(),
                                       rtol=1e-6)
    log_line("done")
    hvd.shutdown()


if __name__ == "__main__":
    main()
