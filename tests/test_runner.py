"""Launcher unit tests (reference: test/single/test_run.py — arg
parsing and command-line construction asserted as strings, no SSH)."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.runner.hosts import assign_ranks, parse_hosts
from horovod_tpu.runner.launch import _ssh_command, build_env, make_parser
from horovod_tpu.runner.hosts import RankInfo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHosts:
    def test_default_localhost(self):
        hs = parse_hosts(None, 4)
        assert len(hs) == 1 and hs[0].host == "localhost" \
            and hs[0].slots == 4

    def test_parse(self):
        hs = parse_hosts("h1:2, h2:3", 5)
        assert [(h.host, h.slots) for h in hs] == [("h1", 2), ("h2", 3)]

    def test_too_few_slots(self):
        with pytest.raises(ValueError, match="slots"):
            parse_hosts("h1:2", 4)

    def test_bad_slots(self):
        with pytest.raises(ValueError):
            parse_hosts("h1:x", 1)
        with pytest.raises(ValueError):
            parse_hosts("h1:0", 1)

    def test_assign_ranks(self):
        infos = assign_ranks(parse_hosts("h1:2,h2:2", 4), 4)
        assert [(i.rank, i.host, i.local_rank, i.cross_rank)
                for i in infos] == [
            (0, "h1", 0, 0), (1, "h1", 1, 0),
            (2, "h2", 0, 1), (3, "h2", 1, 1)]
        assert all(i.local_size == 2 and i.cross_size == 2
                   for i in infos)

    def test_assign_partial_last_host(self):
        infos = assign_ranks(parse_hosts("h1:2,h2:2", 3), 3)
        assert [i.host for i in infos] == ["h1", "h1", "h2"]
        assert infos[2].local_size == 1


class TestEnvAndSsh:
    def test_build_env(self):
        info = RankInfo(1, 4, 1, 2, 0, 2, "h1")
        env = build_env(info, "c:123", {"PATH": "/bin"})
        assert env["HOROVOD_RANK"] == "1"
        assert env["HOROVOD_SIZE"] == "4"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert env["HOROVOD_COORDINATOR_ADDR"] == "c:123"
        assert env["PATH"] == "/bin"

    def test_ssh_command_string(self):
        cmd = _ssh_command("hostB", ["python", "train.py"], 2222)
        assert cmd[0] == "ssh"
        assert "-p" in cmd and "2222" in cmd
        assert cmd[-2] == "hostB"
        remote = cmd[-1]
        # NOTHING env-shaped in the argv: the whole environment rides
        # the stdin pipe (read __HVD_ENV, base64-decode, eval).
        assert "read -r __HVD_ENV" in remote
        assert "base64 -d" in remote
        assert remote.endswith("python train.py")

    def test_env_stdin_payload(self):
        """The stdin env payload carries the full launcher env (minus
        host-specific shell state) plus the secret; nothing of it is
        in the argv (reference contrast: gloo_run inlines the env into
        the remote command — here /proc never sees it)."""
        import base64
        import io
        from horovod_tpu.runner import secret as S
        from horovod_tpu.runner.launch import _write_env_stdin

        class FakeProc:
            def __init__(self):
                self.stdin = io.BytesIO()
                self.stdin.close = lambda: None  # keep readable
        p = FakeProc()
        env = {"HOROVOD_RANK": "2", "MY_DATASET": "/data/x",
               "SSH_AUTH_SOCK": "/tmp/agent", "PWD": "/somewhere",
               "TERMINATION_GRACE": "30", "not an ident": "x"}
        _write_env_stdin(p, env, secret="deadbeef")
        script = base64.b64decode(p.stdin.getvalue()).decode()
        assert "export HOROVOD_RANK=2" in script
        assert "export MY_DATASET=/data/x" in script
        assert f"export {S.ENV_VAR}=deadbeef" in script
        # exact-name blocking must not eat prefixed user vars
        assert "export TERMINATION_GRACE=30" in script
        assert "SSH_AUTH_SOCK" not in script
        assert "PWD=" not in script
        assert "not an ident" not in script

    def test_parser(self):
        args = make_parser().parse_args(
            ["-np", "4", "-H", "h1:4", "python", "t.py"])
        assert args.num_proc == 4 and args.hosts == "h1:4"
        assert args.command == ["python", "t.py"]

    def test_tuning_flags_forward_as_env(self):
        """Reference: horovodrun's tuning flags mirror HOROVOD_* env
        vars and are forwarded to every worker."""
        from horovod_tpu.runner.launch import env_from_flags
        args = make_parser().parse_args([
            "-np", "2",
            "--fusion-threshold-bytes", "1048576",
            "--cycle-time-ms", "2.5",
            "--cache-capacity", "0",
            "--hierarchical-allreduce",
            "--timeline-filename", "/tmp/tl.json",
            "--timeline-mark-cycles",
            "--autotune", "--autotune-log-file", "/tmp/at.csv",
            "--no-stall-check",
            "--stall-shutdown-time-seconds", "120",
            "--log-level", "debug", "--log-hide-timestamp",
            "--controller", "python",
            "python", "t.py"])
        env = env_from_flags(args, base={})
        assert env == {
            "HOROVOD_FUSION_THRESHOLD": "1048576",
            "HOROVOD_CYCLE_TIME": "2.5",
            "HOROVOD_CACHE_CAPACITY": "0",
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_TIMELINE": "/tmp/tl.json",
            "HOROVOD_TIMELINE_MARK_CYCLES": "1",
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_LOG": "/tmp/at.csv",
            "HOROVOD_STALL_CHECK_DISABLE": "1",
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "120.0",
            "HOROVOD_LOG_LEVEL": "debug",
            "HOROVOD_LOG_TIMESTAMP": "0",
            "HOROVOD_CONTROLLER": "python",
        }

    def test_unset_tuning_flags_leave_env_alone(self):
        from horovod_tpu.runner.launch import env_from_flags
        args = make_parser().parse_args(["-np", "2", "python", "t.py"])
        assert env_from_flags(args, base={"KEEP": "1"}) == {"KEEP": "1"}

    def test_every_tuning_flag_maps_to_declared_knob(self):
        """Each flag's target env var must exist in the config
        registry — no flag may write a knob nothing reads."""
        from horovod_tpu.common.config import KNOBS
        from horovod_tpu.runner.launch import _FLAG_ENV_MAP
        declared = {k.env for k in KNOBS}
        for _, var, _ in _FLAG_ENV_MAP:
            assert var in declared, var


def run_launcher(np_, script, extra_env=None, timeout=240):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # children don't need 8 fake devices
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_),
         sys.executable, script],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.integration
class TestRealLaunch:
    @pytest.mark.parametrize("np_", [2, 4])
    def test_two_process_collectives(self, np_, multiproc_data_plane):
        # np=4 additionally exercises a live 2-member SUBSET process
        # set (inline dispatch path) alongside the world controller.
        r = run_launcher(np_, os.path.join("tests", "mp_worker.py"))
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("ALL OK") == np_

    def test_failing_rank_propagates(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import os, sys\n"
            "sys.exit(3 if os.environ['HOROVOD_RANK'] == '1' else 0)\n")
        r = run_launcher(2, str(bad))
        assert r.returncode == 3
        assert "exited with code 3" in r.stdout + r.stderr


class TestDoctor:
    def test_check_build(self):
        from horovod_tpu.runner.doctor import check_build
        out = check_build()
        assert "XLA collectives" in out
        assert "[ ] NCCL" in out
        assert "JAX" in out


class TestSecretAuth:
    """HMAC-authenticated launcher services (reference:
    horovod/runner/common/util/secret.py + BasicService auth)."""

    def test_sign_verify_roundtrip(self):
        from horovod_tpu.runner import secret as S
        k = S.make_secret()
        sig = S.sign(k, b"/rank/h/0")
        assert S.verify(k, b"/rank/h/0", sig)
        assert not S.verify(k, b"/rank/h/1", sig)
        assert not S.verify(k, b"/rank/h/0", "")
        assert not S.verify(k, b"/rank/h/0", "deadbeef")

    def test_rendezvous_rejects_unsigned(self):
        import json
        import urllib.request
        import urllib.error
        from horovod_tpu.runner import secret as S
        from horovod_tpu.runner.elastic.rendezvous import \
            RendezvousServer
        k = S.make_secret()
        srv = RendezvousServer(secret=k)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            # unsigned GET -> 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/world", timeout=5)
            assert ei.value.code == 403
            # unsigned PUT (the write path) -> 403 and no state change
            body = json.dumps({"port": 31337}).encode()
            req = urllib.request.Request(
                f"{base}/notify/evil/0", data=body, method="PUT")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 403
            assert srv.notify_ports() == {}
            # correctly signed requests succeed
            path = "/notify/h/0"
            req = urllib.request.Request(
                f"{base}{path}", data=body, method="PUT",
                headers={S.HEADER: S.sign(k, path.encode() + body)})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 200
            assert srv.notify_ports() == {("h", 0): 31337}
            req = urllib.request.Request(
                f"{base}/world",
                headers={S.HEADER: S.sign(k, b"/world")})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 200
        finally:
            srv.stop()

    def test_notification_listener_rejects_unsigned(self, monkeypatch):
        import json
        import socket as socket_mod
        from horovod_tpu.runner import secret as S
        from horovod_tpu.elastic import notifications
        from horovod_tpu.elastic.worker import NotificationListener
        k = S.make_secret()
        monkeypatch.setenv(S.ENV_VAR, k)
        seen = []
        monkeypatch.setattr(notifications, "notify",
                            lambda info: seen.append(info))
        from horovod_tpu.runner.service import recv_frame, send_frame
        lst = NotificationListener()
        try:
            def poke(obj, key):
                with socket_mod.create_connection(
                        ("127.0.0.1", lst.port), timeout=5) as s:
                    send_frame(s, key, obj)
                    return recv_frame(s, k)  # replies signed with k
            # missigned poke (wrong key): rejected, no notification
            assert poke({"type": "hosts_updated", "epoch": 9},
                        "wrong-key") == {"error": "denied"}
            assert seen == []
            # signed poke: accepted
            assert poke({"type": "hosts_updated", "epoch": 3},
                        k) == {"ok": True}
            assert seen == [{"epoch": 3}]
        finally:
            lst.stop()

    def test_launcher_forwards_secret(self):
        """Every rank of a static launch gets the same HOROVOD_SECRET."""
        import subprocess
        import sys
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import os; print('SECRET', "
                "os.environ.get('HOROVOD_SECRET', '')[:8])")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, "-c", code],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        lines = sorted(ln.split("]", 1)[1] for ln in
                       r.stdout.splitlines() if "SECRET" in ln)
        assert len(lines) == 2
        assert lines[0] == lines[1]
        assert len(lines[0].split()[-1]) == 8


def _ssh_localhost_available() -> bool:
    import subprocess
    try:
        r = subprocess.run(
            ["ssh", "-o", "BatchMode=yes", "-o",
             "StrictHostKeyChecking=no", "-o", "ConnectTimeout=3",
             "localhost", "true"], capture_output=True, timeout=10)
        return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


@pytest.mark.integration
class TestSshLaunch:
    def test_ssh_to_localhost_rank(self):
        """Exercise the remote-ssh spawn path end-to-end by naming the
        host by hostname (not in LOCALHOSTS, so the launcher takes the
        ssh branch) — reference: gloo_run's exec_command over
        util/remote.py."""
        import socket as socket_mod
        import subprocess
        import sys
        if not _ssh_localhost_available():
            pytest.skip("no passwordless ssh to localhost")
        host = socket_mod.gethostname()
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import os; print('RANK', os.environ['HOROVOD_RANK'], "
                "'HOST', os.uname().nodename)")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "-H", f"localhost:1,{host}:1",
             sys.executable, "-c", code],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RANK 0" in r.stdout and "RANK 1" in r.stdout


def _write_fake_ssh(tmp_path):
    """An `ssh` stand-in that execs the remote command locally: parses
    away ssh options, drops the host, and runs the command string
    through sh with stdin passed through — so the launcher's REAL
    remote branch (option assembly, env exports, secret-on-stdin,
    output pumping) is exercised without sshd. Each invocation's argv
    is logged so tests can assert what crossed the 'wire'."""
    shim = tmp_path / "ssh"
    log = tmp_path / "ssh_argv.log"
    shim.write_text(f"""#!/bin/sh
printf '%s\\n' "$@" >> {log}
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-p) shift 2 ;;
    -*) shift ;;
    *) break ;;
  esac
done
# $1 is the host; the rest is the remote command
shift
exec sh -c "$*"
""")
    shim.chmod(0o755)
    return shim, log


@pytest.mark.integration
class TestFakeSshLaunch:
    """Remote-spawn paths driven through a local ssh shim (the image
    has no ssh client; the shim keeps the launcher code path
    identical up to the exec)."""

    def _env(self, tmp_path):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["PATH"] = str(tmp_path) + os.pathsep + env["PATH"]
        return env

    def test_static_launch_remote_branch(self, tmp_path):
        import subprocess
        import sys
        _, log = _write_fake_ssh(tmp_path)
        code = ("import os; print('RANK', os.environ['HOROVOD_RANK'], "
                "'SECRET_SET', bool(os.environ.get('HOROVOD_SECRET')))")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "-H", "localhost:1,fakehost:1",
             sys.executable, "-c", code],
            cwd=REPO, env=self._env(tmp_path), capture_output=True,
            text=True, timeout=240)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RANK 0" in r.stdout and "RANK 1" in r.stdout
        # the worker HAS the secret (delivered over stdin)...
        assert "SECRET_SET True" in r.stdout
        # ...and NO env at all crossed the ssh argv
        argv = log.read_text()
        assert "HOROVOD_SECRET=" not in argv
        assert "HOROVOD_RANK=" not in argv
        assert "read -r __HVD_ENV" in argv

    def test_driver_launch_remote_task_service(self, tmp_path):
        """Probed launch with the task service for 'fakehost' started
        through the ssh shim: registration, NIC probe, election, and
        the run RPC all execute for real."""
        import subprocess
        import sys
        _, log = _write_fake_ssh(tmp_path)
        code = ("import os; print('RANK', os.environ['HOROVOD_RANK'], "
                "'IFACE', os.environ.get('HOROVOD_IFACE', '-'))")
        r = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             "-H", "localhost:1,fakehost:1", "--driver",
             "--start-timeout", "90",
             sys.executable, "-c", code],
            cwd=REPO, env=self._env(tmp_path), capture_output=True,
            text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RANK 0" in r.stdout and "RANK 1" in r.stdout
        argv = log.read_text()
        assert "task_service" in argv
        assert "HOROVOD_SECRET=" not in argv
