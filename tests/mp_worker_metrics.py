"""Worker for the 2-rank metrics-scrape integration test: drives real
negotiated collectives, then scrapes its OWN /metrics endpoint (the
`curl localhost:$HOROVOD_METRICS_PORT/metrics` acceptance path — rank
i serves on port + local_rank) and cross-checks the scraped Prometheus
text against the in-process hvd.metrics() snapshot."""

import os
import re
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? "
    r"(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]Inf|NaN)$")


def main():
    base_port = int(os.environ["HOROVOD_METRICS_PORT"])
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n

    # Exercise the negotiated paths that feed the counters.
    out = hvd.allreduce(jnp.ones(1024, jnp.float32), op=hvd.Sum,
                        name="met0")
    np.testing.assert_allclose(np.asarray(out), float(n))
    hvd.grouped_allreduce([jnp.ones(16), jnp.ones(32)], op=hvd.Sum,
                          name="met1")
    hvd.allgather(jnp.full((r + 1, 2), float(r)), name="met2")
    hvd.broadcast(jnp.arange(8.0), root_rank=0, name="met3")
    hvd.barrier()

    # The endpoint each rank serves: base + local_rank.
    lr = hvd.local_rank()
    port = base_port + max(lr, 0)
    from horovod_tpu.common.basics import state
    assert state().metrics_server is not None, "no metrics server"
    assert state().metrics_server.port == port, (
        state().metrics_server.port, port)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()

    # Valid Prometheus exposition, with the acceptance metrics.
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert _SAMPLE_RE.match(line), f"bad line: {line!r}"
    assert 'hvd_allreduce_bytes_total{pset="0"}' in text, text
    assert "hvd_dispatch_latency_seconds_bucket" in text
    assert "hvd_stalled_tensors 0" in text
    assert "hvd_negotiation_latency_seconds_count" in text

    # The scrape and the in-process snapshot must agree (no ops ran
    # in between).
    snap = hvd.metrics()
    m = re.search(r'^hvd_allreduce_bytes_total\{pset="0"\} (\S+)$',
                  text, re.M)
    scraped = float(m.group(1))
    in_proc = snap["hvd_allreduce_bytes_total"][("0",)]
    assert scraped == in_proc, (scraped, in_proc)
    # 1024 f32 + (16 + 32) f64-or-f32 leaves were submitted; at least
    # the single allreduce's 4096 raw bytes must be there.
    assert in_proc >= 4096, in_proc
    assert snap["hvd_world_size"][()] == n
    assert snap["hvd_rank"][()] == r
    assert snap["hvd_fused_batches_total"][("ar",)] >= 1

    print(f"worker rank={r}: METRICS ALL OK")
    hvd.shutdown()


if __name__ == "__main__":
    main()
