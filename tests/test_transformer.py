"""Flagship transformer: sharded (tp/sp/ep) numerics vs single-device
oracle, and the full sharded train step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models import flagship
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel import MeshSpec, build_mesh

SMALL = tfm.TransformerConfig(
    vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    head_dim=8, d_ff=64, max_seq=32, dtype=jnp.float32)


def oracle_loss(cfg, params, batch):
    """Single-device loss: same config with all strategy axes off."""
    cfg1 = dataclasses.replace(cfg, tp_axis=None, sp_axis=None,
                               ep_axis=None)
    return tfm.loss_fn(cfg1, params, batch)


def make_host_batch(cfg, B, L, seed=1):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (B, L), 0, cfg.vocab, jnp.int32)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


class TestShardedLossMatchesOracle:
    @pytest.mark.parametrize("spec", [
        MeshSpec(tensor=2),                 # dp4 × tp2
        MeshSpec(seq=2),                    # dp4 × sp2
        MeshSpec(tensor=2, seq=2),          # dp2 × tp2 × sp2
    ])
    def test_dense(self, spec):
        mesh = build_mesh(spec)
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.sgd(0.1))
        batch_host = make_host_batch(cfg, 8, 32)

        params_host = jax.tree.map(np.asarray, jax.device_get(params))
        l0 = float(oracle_loss(cfg, params_host, batch_host))

        batch = flagship.make_batch(cfg, mesh, 8, 32, seed=1)
        # same tokens for oracle and sharded run
        batch = {"tokens": jax.device_put(
                     batch_host["tokens"], batch["tokens"].sharding),
                 "targets": jax.device_put(
                     batch_host["targets"], batch["targets"].sharding)}
        new_params, _, metrics = step(params, opt_state, batch)
        np.testing.assert_allclose(float(metrics["loss"]), l0,
                                   rtol=1e-4, atol=1e-4)

    def test_moe_full_mesh(self):
        """tp×sp×ep all live with MoE — regression for the missing
        tp-psum on the expert down-projection."""
        cfg0 = dataclasses.replace(SMALL, moe=True, n_experts=4,
                                   capacity_factor=8.0)
        mesh = build_mesh(MeshSpec(tensor=2, seq=2, expert=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, cfg0, optax.adam(1e-2))
        batch = flagship.make_batch(cfg, mesh, 8, 32)
        losses = []
        for _ in range(5):
            params, opt_state, m = step(params, opt_state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    def test_moe_ep(self):
        cfg0 = dataclasses.replace(SMALL, moe=True, n_experts=4,
                                   capacity_factor=8.0)
        mesh = build_mesh(MeshSpec(expert=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, cfg0, optax.sgd(0.1))
        batch_host = make_host_batch(cfg, 8, 32)
        params_host = jax.tree.map(np.asarray, jax.device_get(params))
        l0 = float(oracle_loss(cfg, params_host, batch_host))
        spec_sh = flagship.batch_spec(mesh)
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, spec_sh)
        batch = {k: jax.device_put(v, sh) for k, v in batch_host.items()}
        _, _, metrics = step(params, opt_state, batch)
        # EP shards tokens per expert-rank: routing/capacity identical
        # only with generous capacity; loss must match to fp32 noise.
        np.testing.assert_allclose(float(metrics["loss"]), l0,
                                   rtol=2e-3, atol=2e-3)


class TestRematModes:
    """remat_mode='mlp_only' (attention residuals saved, FFN
    recomputed) must be numerically identical to full remat — only
    the backward's save/recompute split changes."""

    def test_mlp_only_matches_full(self):
        import dataclasses
        from horovod_tpu.models import transformer as tfm
        base = tfm.TransformerConfig(
            vocab=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
            head_dim=8, d_ff=64, max_seq=16, moe=False,
            dtype=jnp.float32, remat=True,
            tp_axis=None, sp_axis=None, ep_axis=None)
        params = tfm.init_params(base, jax.random.PRNGKey(0))
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 base.vocab, jnp.int32)
        batch = {"tokens": tok, "targets": jnp.roll(tok, -1, axis=1)}

        def lg(cfg):
            return jax.value_and_grad(
                lambda p: tfm.loss_fn(cfg, p, batch))(params)

        l_full, g_full = lg(base)
        l_mlp, g_mlp = lg(dataclasses.replace(base,
                                              remat_mode="mlp_only"))
        np.testing.assert_allclose(float(l_full), float(l_mlp),
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g_full),
                        jax.tree_util.tree_leaves(g_mlp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


class TestFSDP:
    """ZeRO-3 on TPU (parallel/fsdp.py + make_flagship_fsdp):
    parameters AND optimizer state sharded over the fsdp mesh axis;
    XLA's partitioner derives the all-gather(param) /
    reduce-scatter(grad) schedule; numerics match the replicated
    run. The reference has no FSDP (SURVEY.md §2.6) — TPU-native
    bonus."""

    @staticmethod
    def _has_fsdp(spec) -> bool:
        return any(
            a == "fsdp" or (isinstance(a, tuple) and "fsdp" in a)
            for a in spec if a is not None)

    def test_params_and_opt_state_actually_sharded(self):
        mesh = build_mesh(MeshSpec(fsdp=2))  # dp4 x fsdp2
        cfg, params, opt_state, step = flagship.make_flagship_fsdp(
            mesh, SMALL, optax.adam(1e-2))
        assert self._has_fsdp(params["embed"].sharding.spec), \
            params["embed"].sharding
        # every weight matrix is sharded (only tiny norm vectors may
        # stay replicated)
        for path, p in jax.tree_util.tree_leaves_with_path(params):
            if p.ndim >= 2:
                assert self._has_fsdp(p.sharding.spec), \
                    (jax.tree_util.keystr(path), p.sharding)
        # optimizer moments inherit the ZeRO sharding
        mu_embed = opt_state[0].mu["embed"]
        assert self._has_fsdp(mu_embed.sharding.spec), mu_embed.sharding

    def test_fsdp_compiles_gathers(self):
        """The compiled step must contain fsdp collectives — proof the
        parameters really live sharded and are gathered for use."""
        mesh = build_mesh(MeshSpec(fsdp=2))
        cfg, params, opt_state, step = flagship.make_flagship_fsdp(
            mesh, SMALL, optax.sgd(0.5))
        batch = flagship.make_batch(cfg, mesh, 8, 32)
        hlo = step.lower(params, opt_state, batch).compile().as_text()
        assert "all-gather" in hlo or "all-gather-start" in hlo, \
            hlo[:2000]

    def test_fsdp_x_tp_explicit_path(self):
        """fsdp AND tensor both live on the explicit-collective path
        (round-3 verdict Next #5): parameters shard over fsdp, the
        step all-gathers them inside the differentiated region (so
        the transpose is the grad reduce-scatter), tp collectives run
        as usual — and one SGD step equals the single-device oracle."""
        mesh = build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.sgd(0.5))
        # params actually sharded over fsdp
        assert TestFSDP._has_fsdp(params["embed"].sharding.spec), \
            params["embed"].sharding
        # and the compiled step contains fsdp collectives
        batch_host = make_host_batch(cfg, 8, 32)
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, flagship.batch_spec(mesh))
        batch = {k: jax.device_put(v, sh)
                 for k, v in batch_host.items()}
        hlo = step.lower(params, opt_state, batch).compile().as_text()
        assert "all-gather" in hlo or "all-gather-start" in hlo

        params_host = jax.tree.map(np.asarray, jax.device_get(params))
        new_params, _, metrics = step(params, opt_state, batch)

        # oracle: replicated single-program SGD step on the host params
        def mean_loss(p):
            return oracle_loss(cfg, p, batch_host)
        l0, g = jax.value_and_grad(mean_loss)(params_host)
        np.testing.assert_allclose(float(metrics["loss"]), float(l0),
                                   rtol=1e-4, atol=1e-4)
        want = jax.tree.map(lambda p, gg: p - 0.5 * gg, params_host, g)
        got = jax.tree.map(np.asarray, jax.device_get(new_params))
        jax.tree.map(
            lambda w, o: np.testing.assert_allclose(
                o, w, rtol=2e-3, atol=2e-4), want, got)

    def test_fsdp_step_matches_replicated(self):
        """One SGD step under ZeRO-3 sharding must equal the
        single-device full-batch step: fsdp changes layout, never
        math."""
        mesh = build_mesh(MeshSpec(fsdp=2))
        cfg, params, opt_state, step = flagship.make_flagship_fsdp(
            mesh, SMALL, optax.sgd(0.5))
        batch_host = make_host_batch(cfg, 8, 32)
        params_host = jax.tree.map(np.asarray, jax.device_get(params))

        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, flagship.batch_spec(mesh))
        batch = {k: jax.device_put(v, sh) for k, v in batch_host.items()}
        new_params, _, metrics = step(params, opt_state, batch)
        new_params_host = jax.tree.map(np.asarray,
                                       jax.device_get(new_params))

        l0 = float(oracle_loss(cfg, params_host, batch_host))
        np.testing.assert_allclose(float(metrics["loss"]), l0,
                                   rtol=1e-4, atol=1e-4)
        grads = jax.grad(
            lambda p: oracle_loss(cfg, p, batch_host))(params_host)
        oracle = jax.tree.map(lambda p, g: p - 0.5 * g, params_host,
                              grads)
        flat2 = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(oracle))
        for path, v in jax.tree_util.tree_leaves_with_path(
                new_params_host):
            np.testing.assert_allclose(
                np.asarray(v),
                np.asarray(flat2[jax.tree_util.keystr(path)]),
                rtol=2e-4, atol=2e-4,
                err_msg=jax.tree_util.keystr(path))


class TestTrainingConverges:
    def test_loss_decreases_sharded(self):
        mesh = build_mesh(MeshSpec(tensor=2, seq=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.adam(1e-2))
        batch = flagship.make_batch(cfg, mesh, 8, 32)
        losses = []
        for _ in range(10):
            params, opt_state, metrics = step(params, opt_state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_sharded_step_matches_replicated_step(self):
        """One SGD step on dp2×tp2×sp2 must produce the same params as
        one full-batch single-device step."""
        mesh = build_mesh(MeshSpec(tensor=2, seq=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.sgd(0.5))
        batch_host = make_host_batch(cfg, 8, 32)
        params_host = jax.tree.map(np.asarray, jax.device_get(params))

        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, flagship.batch_spec(mesh))
        batch = {k: jax.device_put(v, sh) for k, v in batch_host.items()}
        new_params, _, _ = step(params, opt_state, batch)
        new_params_host = jax.tree.map(np.asarray,
                                       jax.device_get(new_params))

        grads = jax.grad(
            lambda p: oracle_loss(cfg, p, batch_host))(params_host)
        oracle = jax.tree.map(lambda p, g: p - 0.5 * g, params_host,
                              grads)
        flat1 = jax.tree_util.tree_leaves_with_path(new_params_host)
        flat2 = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(oracle))
        for path, v in flat1:
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat2[jax.tree_util.keystr(path)]),
                rtol=2e-4, atol=2e-4, err_msg=jax.tree_util.keystr(path))
