"""Flagship transformer: sharded (tp/sp/ep) numerics vs single-device
oracle, and the full sharded train step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models import flagship
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel import MeshSpec, build_mesh

SMALL = tfm.TransformerConfig(
    vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    head_dim=8, d_ff=64, max_seq=32, dtype=jnp.float32)


def oracle_loss(cfg, params, batch):
    """Single-device loss: same config with all strategy axes off."""
    cfg1 = dataclasses.replace(cfg, tp_axis=None, sp_axis=None,
                               ep_axis=None)
    return tfm.loss_fn(cfg1, params, batch)


def make_host_batch(cfg, B, L, seed=1):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (B, L), 0, cfg.vocab, jnp.int32)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


class TestShardedLossMatchesOracle:
    @pytest.mark.parametrize("spec", [
        MeshSpec(tensor=2),                 # dp4 × tp2
        MeshSpec(seq=2),                    # dp4 × sp2
        MeshSpec(tensor=2, seq=2),          # dp2 × tp2 × sp2
    ])
    def test_dense(self, spec):
        mesh = build_mesh(spec)
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.sgd(0.1))
        batch_host = make_host_batch(cfg, 8, 32)

        params_host = jax.tree.map(np.asarray, jax.device_get(params))
        l0 = float(oracle_loss(cfg, params_host, batch_host))

        batch = flagship.make_batch(cfg, mesh, 8, 32, seed=1)
        # same tokens for oracle and sharded run
        batch = {"tokens": jax.device_put(
                     batch_host["tokens"], batch["tokens"].sharding),
                 "targets": jax.device_put(
                     batch_host["targets"], batch["targets"].sharding)}
        new_params, _, metrics = step(params, opt_state, batch)
        np.testing.assert_allclose(float(metrics["loss"]), l0,
                                   rtol=1e-4, atol=1e-4)

    def test_moe_full_mesh(self):
        """tp×sp×ep all live with MoE — regression for the missing
        tp-psum on the expert down-projection."""
        cfg0 = dataclasses.replace(SMALL, moe=True, n_experts=4,
                                   capacity_factor=8.0)
        mesh = build_mesh(MeshSpec(tensor=2, seq=2, expert=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, cfg0, optax.adam(1e-2))
        batch = flagship.make_batch(cfg, mesh, 8, 32)
        losses = []
        for _ in range(5):
            params, opt_state, m = step(params, opt_state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    def test_moe_ep(self):
        cfg0 = dataclasses.replace(SMALL, moe=True, n_experts=4,
                                   capacity_factor=8.0)
        mesh = build_mesh(MeshSpec(expert=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, cfg0, optax.sgd(0.1))
        batch_host = make_host_batch(cfg, 8, 32)
        params_host = jax.tree.map(np.asarray, jax.device_get(params))
        l0 = float(oracle_loss(cfg, params_host, batch_host))
        spec_sh = flagship.batch_spec(mesh)
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, spec_sh)
        batch = {k: jax.device_put(v, sh) for k, v in batch_host.items()}
        _, _, metrics = step(params, opt_state, batch)
        # EP shards tokens per expert-rank: routing/capacity identical
        # only with generous capacity; loss must match to fp32 noise.
        np.testing.assert_allclose(float(metrics["loss"]), l0,
                                   rtol=2e-3, atol=2e-3)


class TestTrainingConverges:
    def test_loss_decreases_sharded(self):
        mesh = build_mesh(MeshSpec(tensor=2, seq=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.adam(1e-2))
        batch = flagship.make_batch(cfg, mesh, 8, 32)
        losses = []
        for _ in range(10):
            params, opt_state, metrics = step(params, opt_state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_sharded_step_matches_replicated_step(self):
        """One SGD step on dp2×tp2×sp2 must produce the same params as
        one full-batch single-device step."""
        mesh = build_mesh(MeshSpec(tensor=2, seq=2))
        cfg, params, opt_state, step = flagship.make_flagship(
            mesh, SMALL, optax.sgd(0.5))
        batch_host = make_host_batch(cfg, 8, 32)
        params_host = jax.tree.map(np.asarray, jax.device_get(params))

        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, flagship.batch_spec(mesh))
        batch = {k: jax.device_put(v, sh) for k, v in batch_host.items()}
        new_params, _, _ = step(params, opt_state, batch)
        new_params_host = jax.tree.map(np.asarray,
                                       jax.device_get(new_params))

        grads = jax.grad(
            lambda p: oracle_loss(cfg, p, batch_host))(params_host)
        oracle = jax.tree.map(lambda p, g: p - 0.5 * g, params_host,
                              grads)
        flat1 = jax.tree_util.tree_leaves_with_path(new_params_host)
        flat2 = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(oracle))
        for path, v in flat1:
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat2[jax.tree_util.keystr(path)]),
                rtol=2e-4, atol=2e-4, err_msg=jax.tree_util.keystr(path))
