"""Live weight pipeline tests (weights.py + serving.py adoption):
publisher round-trip/digest/sharding, corrupt + torn snapshot
rejection with the worker still serving its previous version,
verified rollback and recovery-path repair, version GC, subscriber
seq semantics (republish = retry), the epoch-fenced hot-swap under
live traffic with zero dropped requests, worker death mid-swap, the
trainer commit-path publication hook, the armed-or-not contract of
the `weights.publish` / `weights.adopt` seams, journal event
registration (old incident artifacts stay byte-identical), and the
committed weight-swap bench artifact's pins."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import faults, journal
from horovod_tpu import weights as W
from horovod_tpu.metrics import REGISTRY
from horovod_tpu.serving import ServingFrontend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_ARTIFACT = os.path.join(REPO, "benchmarks",
                              "BENCH_weightswap_r17.json")
TRAJECTORY = os.path.join(REPO, "benchmarks", "BENCH_trajectory.json")

D = 4  # feature width for every frontend in this file


def _forward(params, x):
    import jax.numpy as jnp
    return jnp.tanh(x @ params["w"]) + params["b"]


def _params(scale=1.0, bias=0.0):
    # explicit float32: conftest enables x64, but the remote-worker
    # subprocesses (no conftest) build float32 bootstraps — and the
    # structure contract rejects dtype drift by design
    import jax.numpy as jnp
    return {"w": jnp.eye(D, dtype=jnp.float32) * scale,
            "b": jnp.full((D,), bias, dtype=jnp.float32)}


@pytest.fixture(autouse=True)
def _clean_fault_and_journal_state():
    yield
    faults.configure("", seed=0)
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None


def _base_env(tmp_path=None, **over):
    env = {
        "HOROVOD_SERVING_MAX_BATCH": "4",
        "HOROVOD_SERVING_LATENCY_BUDGET_MS": "5",
        "HOROVOD_SERVING_MIN_WORKERS": "1",
        "HOROVOD_SERVING_MAX_WORKERS": "4",
        "HOROVOD_SERVING_SCALE_INTERVAL_S": "0.05",
        "HOROVOD_SERVING_WORKER_TIMEOUT_S": "30",
        "HOROVOD_WEIGHTS_POLL_MS": "20",
    }
    if tmp_path is not None:
        jdir = os.path.join(str(tmp_path), "journal")
        os.makedirs(jdir, exist_ok=True)
        env["HOROVOD_JOURNAL_DIR"] = jdir
    env.update({k: str(v) for k, v in over.items()})
    return env


def _journal_events(tmp_path, role="serving"):
    path = os.path.join(str(tmp_path), "journal",
                        f"journal-{role}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _wait(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# -- publisher / subscriber ------------------------------------------------


class TestPublisher:
    def test_publish_poll_load_round_trip(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        p = _params(3.0, 0.5)
        v = pub.publish(p, step=42)
        assert v.seq == 1 and v.step == 42
        sub = W.WeightSubscriber(d)
        got = sub.poll()
        assert got == v
        assert sub.poll() is None        # each seq surfaces once
        names, treedef = W.tree_spec(p)
        tree = W.rebuild(sub.load_named(got), names, treedef)
        np.testing.assert_allclose(np.asarray(tree["w"]),
                                   np.eye(D) * 3.0)
        np.testing.assert_allclose(np.asarray(tree["b"]), 0.5)

    def test_digest_is_content_addressed(self, tmp_path):
        pub = W.WeightPublisher(str(tmp_path / "w"))
        v1 = pub.publish(_params(1.0), 1)
        v2 = pub.publish(_params(2.0), 2)
        v3 = pub.publish(_params(1.0), 3)
        assert v1.digest != v2.digest
        assert v1.digest == v3.digest    # same bytes, same identity
        assert v3.seq == 3               # but a fresh epoch

    def test_sharding_splits_and_reassembles(self, tmp_path):
        import jax.numpy as jnp
        d = str(tmp_path / "w")
        # ~1 KiB leaves against the 1 MiB floor would never split;
        # force multi-shard with many leaves via a tiny target.
        pub = W.WeightPublisher(d)
        pub._shard_bytes = 256
        p = {f"l{i}": jnp.full((16,), float(i)) for i in range(8)}
        v = pub.publish(p, 1)
        man = W.load_manifest(d, v)
        assert len(man["shards"]) > 1
        names, treedef = W.tree_spec(p)
        tree = W.rebuild(W.load_named(d, v), names, treedef)
        for i in range(8):
            np.testing.assert_allclose(np.asarray(tree[f"l{i}"]),
                                       float(i))

    def test_corrupt_shard_rejected(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        faults.configure("weights.publish:corrupt:at=1", seed=1)
        v = pub.publish(_params(), 1)
        faults.configure("", seed=0)
        with pytest.raises(W.WeightIntegrityError):
            W.load_named(d, v)

    def test_torn_shard_rejected(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        faults.configure("weights.publish:torn:at=1", seed=1)
        v = pub.publish(_params(), 1)
        faults.configure("", seed=0)
        with pytest.raises(W.WeightIntegrityError) as ei:
            W.load_named(d, v)
        assert W.rejection_reason(ei.value) == "torn"

    def test_structure_drift_rejected(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        v = pub.publish(_params(), 1)
        other = {"w": np.eye(D), "extra": np.zeros(2)}
        names, treedef = W.tree_spec(other)
        with pytest.raises(W.WeightStructureError):
            W.rebuild(W.load_named(d, v), names, treedef)

    def test_dtype_drift_rejected(self, tmp_path):
        # a trainer that changed precision must not be adopted by a
        # pool whose executables were compiled for the old dtype
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        v = pub.publish({"w": np.eye(D, dtype=np.float64)}, 1)
        boot = {"w": np.eye(D, dtype=np.float32)}
        names, treedef = W.tree_spec(boot)
        with pytest.raises(W.WeightStructureError):
            W.rebuild(W.load_named(d, v), names, treedef,
                      W.leaf_spec(boot))

    def test_rollback_restores_previous_digest(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        v1 = pub.publish(_params(1.0), 1)
        v2 = pub.publish(_params(2.0), 2)
        rb = pub.rollback()
        assert rb.digest == v1.digest
        assert rb.seq > v2.seq           # a fresh epoch: pool adopts
        sub = W.WeightSubscriber(d)
        assert sub.poll().digest == v1.digest
        names, treedef = W.tree_spec(_params())
        tree = W.rebuild(sub.load_named(rb), names, treedef)
        np.testing.assert_allclose(np.asarray(tree["w"]), np.eye(D))

    def test_repair_repoints_damaged_current(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(d)
        v1 = pub.publish(_params(1.0), 1)
        faults.configure("weights.publish:corrupt:at=1", seed=1)
        pub.publish(_params(2.0), 2)
        faults.configure("", seed=0)
        rep = pub.repair()
        assert rep is not None and rep.digest == v1.digest
        assert pub.repair() is None      # now healthy: no-op
        W.load_named(d, W._read_current(d))   # verifies clean

    def test_gc_keeps_n_versions(self, tmp_path):
        d = str(tmp_path / "w")
        pub = W.WeightPublisher(
            d, env={"HOROVOD_WEIGHTS_KEEP": "2"})
        for i in range(5):
            pub.publish(_params(float(i + 1)), i)
        vdirs = [n for n in os.listdir(d) if n.startswith("v")]
        assert len(vdirs) == 2
        # the live version always survives GC
        cur = W._read_current(d)
        assert cur.dir in vdirs

    def test_seq_resumes_across_publisher_restart(self, tmp_path):
        d = str(tmp_path / "w")
        v1 = W.WeightPublisher(d).publish(_params(1.0), 1)
        v2 = W.WeightPublisher(d).publish(_params(2.0), 2)
        assert v2.seq == v1.seq + 1      # monotonic epoch across


# -- fault seams: armed-or-not (negative-control) contract -----------------


class TestWeightSeams:
    def test_publish_seam_disarmed_fires_nothing(self, tmp_path):
        assert not faults.active()
        before = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
        W.WeightPublisher(str(tmp_path / "w")).publish(_params(), 1)
        after = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
        assert before == after

    def test_publish_seam_error_counted(self, tmp_path):
        pub = W.WeightPublisher(str(tmp_path / "w"))
        faults.configure("weights.publish:error:at=1", seed=1)
        with pytest.raises(W.WeightError):
            pub.publish(_params(), 1)
        fired = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
        assert fired.get(("weights.publish", "error"), 0) >= 1
        # the failed attempt left no CURRENT pointer behind
        assert W._read_current(pub.dir) is None

    def test_adopt_seam_fires_armed_or_not(self, tmp_path):
        # the seam is on the adoption path regardless of pipeline
        # feature flags — same contract as numerics.grad
        faults.configure("weights.adopt:delay:ms=1,at=1", seed=1)
        faults.fire("weights.adopt", tag="w0")
        fired = REGISTRY.snapshot().get("hvd_faults_fired_total", {})
        assert fired.get(("weights.adopt", "delay"), 0) >= 1

    def test_illegal_action_rejected_at_parse(self):
        with pytest.raises(ValueError):
            faults.configure("weights.adopt:torn:at=1", seed=1)


# -- serving adoption: the epoch-fenced hot-swap ---------------------------


class TestServingHotSwap:
    def _frontend(self, tmp_path, wdir, **over):
        env = _base_env(tmp_path, **over)
        return ServingFrontend(_forward, (D,), env=env,
                               autoscale=False, params=_params(),
                               weights=wdir)

    def test_swap_under_traffic_zero_dropped(self, tmp_path):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        v1 = pub.publish(_params(1.0), 100)
        env = _base_env(tmp_path, HOROVOD_SERVING_MIN_WORKERS=2,
                        HOROVOD_SERVING_TRACE=1)
        fe = ServingFrontend(_forward, (D,), env=env,
                             autoscale=False, params=_params(),
                             weights=wdir)
        try:
            x = np.ones((D,), np.float32)
            rows1 = [fe.submit(x).result(timeout=30)
                     for _ in range(8)]
            v2 = pub.publish(_params(2.0, 1.0), 200)
            assert _wait(lambda: all(
                w["digest"] == v2.digest for w in
                fe.stats()["weights"]["workers"].values()))
            rows2 = [fe.submit(x).result(timeout=30)
                     for _ in range(8)]
            # the swap changed what the pool computes
            np.testing.assert_allclose(
                rows1[0], np.tanh(np.ones(D)), atol=1e-6)
            np.testing.assert_allclose(
                rows2[0], np.tanh(2.0 * np.ones(D)) + 1.0,
                atol=1e-6)
            st = fe.stats()
            assert st["dropped"] == 0
            assert st["weights"]["swaps"] >= 2
            assert st["weights"]["rejections"] == 0
            # the epoch fence, witnessed by the trace: every request
            # was served under exactly one published digest
            digs = {r["weights"] for r in fe.traces()}
            assert digs <= {v1.digest, v2.digest}
            assert v2.digest in digs
        finally:
            fe.close()
        adopted = [e for e in _journal_events(tmp_path)
                   if e["type"] == "weights_adopted"]
        assert {e["digest"] for e in adopted} >= {v2.digest}

    def test_corrupt_publish_rejected_pool_keeps_old(self, tmp_path):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        v1 = pub.publish(_params(1.0), 1)
        fe = self._frontend(tmp_path, wdir)
        try:
            assert _wait(lambda:
                         fe.stats()["weights"]["swaps"] >= 1)
            faults.configure("weights.publish:corrupt:at=1", seed=1)
            pub.publish(_params(5.0), 2)
            faults.configure("", seed=0)
            assert _wait(lambda:
                         fe.stats()["weights"]["rejections"] >= 1)
            # degraded, not down: still serving v1
            st = fe.stats()["weights"]
            assert all(w["digest"] == v1.digest
                       for w in st["workers"].values())
            x = np.ones((D,), np.float32)
            np.testing.assert_allclose(
                fe.submit(x).result(timeout=30),
                np.tanh(np.ones(D)), atol=1e-6)
            # the publisher's retry (a fresh seq) converges the pool
            v3 = pub.publish(_params(5.0), 3)
            assert _wait(lambda: all(
                w["digest"] == v3.digest for w in
                fe.stats()["weights"]["workers"].values()))
            assert fe.stats()["dropped"] == 0
        finally:
            fe.close()
        rej = [e for e in _journal_events(tmp_path)
               if e["type"] == "weights_rejected"]
        assert rej and rej[0]["reason"] == "digest"
        assert rej[0]["serving"] == v1.digest

    def test_worker_death_mid_swap_pool_recovers(self, tmp_path):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        pub.publish(_params(1.0), 1)
        env = _base_env(tmp_path, HOROVOD_SERVING_MIN_WORKERS=2)
        fe = ServingFrontend(_forward, (D,), env=env,
                             autoscale=True, params=_params(),
                             weights=wdir)
        try:
            assert _wait(lambda:
                         fe.stats()["weights"]["swaps"] >= 2)
            faults.configure("weights.adopt:error:at=1", seed=1)
            v2 = pub.publish(_params(2.0), 2)
            x = np.ones((D,), np.float32)
            rows = [fe.submit(x).result(timeout=30)
                    for _ in range(8)]
            assert len(rows) == 8
            fired = REGISTRY.snapshot().get(
                "hvd_faults_fired_total", {})
            assert fired.get(("weights.adopt", "error"), 0) >= 1
            # the autoscaler restores the floor and the respawned
            # member adopts v2; the pool converges
            assert _wait(lambda: (
                len(fe.stats()["weights"]["workers"]) >= 2
                and all(w["digest"] == v2.digest for w in
                        fe.stats()["weights"]["workers"].values())))
            assert fe.stats()["dropped"] == 0
        finally:
            fe.close()

    def test_rollback_end_to_end(self, tmp_path):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        v1 = pub.publish(_params(1.0), 1)
        v2 = pub.publish(_params(2.0), 2)
        fe = self._frontend(tmp_path, wdir)
        try:
            assert _wait(lambda: all(
                w["digest"] == v2.digest for w in
                fe.stats()["weights"]["workers"].values()))
            rb = pub.rollback()
            assert rb.digest == v1.digest
            assert _wait(lambda: all(
                w["digest"] == v1.digest for w in
                fe.stats()["weights"]["workers"].values()))
            x = np.ones((D,), np.float32)
            np.testing.assert_allclose(
                fe.submit(x).result(timeout=30),
                np.tanh(np.ones(D)), atol=1e-6)
        finally:
            fe.close()

    def test_stats_staleness_and_no_recompile(self, tmp_path):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        pub.publish(_params(1.0), 10)
        fe = self._frontend(tmp_path, wdir)
        try:
            assert _wait(lambda:
                         fe.stats()["weights"]["swaps"] >= 1)
            compiles0 = fe.stats()["compiles"]
            pub.publish(_params(2.0), 30)
            assert _wait(lambda:
                         fe.stats()["weights"]["swaps"] >= 2)
            st = fe.stats()
            # hot-swap must not recompile: executables are
            # specialized on shapes only, which adoption preserves
            assert st["compiles"] == compiles0
            w = next(iter(st["weights"]["workers"].values()))
            assert w["staleness_steps"] == 0
            assert st["weights"]["target_step"] == 30
        finally:
            fe.close()

    def test_params_without_weights_is_static(self, tmp_path):
        # two-arg forward with a fixed tree: no watcher, no target
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             autoscale=False, params=_params(3.0))
        try:
            x = np.ones((D,), np.float32)
            np.testing.assert_allclose(
                fe.submit(x).result(timeout=30),
                np.tanh(3.0 * np.ones(D)), atol=1e-6)
            assert "weights" not in fe.stats()
        finally:
            fe.close()

    def test_weights_requires_params(self, tmp_path):
        with pytest.raises(ValueError):
            ServingFrontend(_forward, (D,),
                            env=_base_env(tmp_path),
                            start_pool=False, autoscale=False,
                            weights=str(tmp_path / "w"))


# -- remote pool member: a REAL process death mid-swap ---------------------


def _spawn_weighted_worker(port, secret, wid, wdir, extra_env=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SERVING_TEST_STANDALONE"] = "1"
    env["SERVING_TEST_ADDR"] = "127.0.0.1"
    env["SERVING_TEST_PORT"] = str(port)
    env["SERVING_TEST_SECRET"] = secret
    env["SERVING_TEST_DMODEL"] = str(D)
    env["SERVING_TEST_WID"] = wid
    env["SERVING_TEST_WEIGHTS_DIR"] = wdir
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable,
         os.path.join("tests", "serving_chaos_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.integration
def test_remote_worker_crash_mid_swap_zero_dropped(tmp_path):
    """Two real worker processes serve the two-arg live-weight
    forward over the wire; a version is published mid-traffic and
    one member is seeded `weights.adopt:crash` — a REAL process
    death (os._exit) mid-swap. The survivor adopts, the dead
    member's in-flight batch is requeued, and every request
    completes — zero dropped, no batch mixing versions."""
    jdir = tmp_path / "journal"
    jdir.mkdir()
    wdir = str(tmp_path / "w")
    env = _base_env(None, HOROVOD_SERVING_WORKER_TIMEOUT_S="1",
                    HOROVOD_SERVING_TRACE="1")
    env["HOROVOD_JOURNAL_DIR"] = str(jdir)
    boot = _params()                     # matches the worker's
    fe = ServingFrontend(_forward, (D,), env=env, params=boot,
                         start_pool=False, autoscale=False)
    boot_digest = fe._params0_digest
    procs = []
    try:
        port, secret = fe.serve_endpoint()
        wa = _spawn_weighted_worker(
            port, secret, "wA", wdir,
            {"HOROVOD_FAULTS": "weights.adopt:crash:at=1",
             "HOROVOD_FAULTS_SEED": "3",
             "HOROVOD_JOURNAL_DIR": str(jdir)})
        wb = _spawn_weighted_worker(
            port, secret, "wB", wdir,
            {"HOROVOD_JOURNAL_DIR": str(jdir)})
        procs = [wa, wb]
        rng = np.random.RandomState(7)
        xs = [rng.randn(D).astype(np.float32) for _ in range(10)]
        futs = [fe.submit(x) for x in xs]
        for f in futs:
            f.result(timeout=120)        # both members live, boot
        v1 = W.WeightPublisher(wdir).publish(
            _params(2.0, 1.0), step=50)
        xs2 = [rng.randn(D).astype(np.float32) for _ in range(14)]
        futs2 = []
        for x in xs2:
            futs2.append(fe.submit(x))
            time.sleep(0.02)
        for f in futs2:
            f.result(timeout=120)
        s = fe.stats()
        assert wa.wait(timeout=60) == 43, \
            "wA should die on the adopt seam"
    finally:
        fe.close()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert wb.returncode == 0, wb.stdout.read()
    assert s["completed"] == 24 and s["failed"] == 0
    assert s["dropped"] == 0
    # epoch fence across the wire: every traced batch executed under
    # exactly one digest, all from the known version set
    digs = {r["weights"] for r in fe.traces()}
    assert digs <= {boot_digest, v1.digest}
    assert v1.digest in digs             # the survivor converged
    # the dead member's journal attributes the mid-swap death
    wa_events = _journal_events(tmp_path, role="serving-wA")
    fired = [e for e in wa_events if e["type"] == "fault_fired"]
    assert fired and fired[0]["point"] == "weights.adopt"
    assert fired[0]["action"] == "crash"
    # the survivor journaled its adoption of the published version
    wb_events = _journal_events(tmp_path, role="serving-wB")
    adopted = [e for e in wb_events
               if e["type"] == "weights_adopted"]
    assert adopted and adopted[0]["digest"] == v1.digest


# -- trainer commit-path publication ---------------------------------------


class TestCommitPathPublish:
    def test_maybe_publish_rides_commit(self, tmp_path, monkeypatch):
        import jax.numpy as jnp
        from horovod_tpu.elastic.state import JaxState
        wdir = str(tmp_path / "w")
        monkeypatch.setenv("HOROVOD_WEIGHTS_DIR", wdir)
        monkeypatch.setenv("HOROVOD_WEIGHTS_PUBLISH_EVERY", "2")
        st = JaxState(params={"w": jnp.ones(D)}, step=0)
        st.commit()                      # commit 1: always publishes
        cur = W._read_current(wdir)
        assert cur is not None and cur.seq == 1
        st.params = {"w": jnp.full(D, 2.0)}
        st.step = 1
        st.commit()                      # commit 2: off-cadence
        assert W._read_current(wdir).seq == 1
        st.params = {"w": jnp.full(D, 3.0)}
        st.step = 2
        st.commit()                      # commit 3: publishes
        cur = W._read_current(wdir)
        assert cur.seq == 2 and cur.step == 2
        named = W.load_named(wdir, cur)
        assert len(named) == 1
        np.testing.assert_allclose(named[0][1], np.full(D, 3.0))

    def test_disarmed_commit_does_not_publish(self, tmp_path,
                                              monkeypatch):
        import jax.numpy as jnp
        from horovod_tpu.elastic.state import JaxState
        monkeypatch.delenv("HOROVOD_WEIGHTS_DIR", raising=False)
        st = JaxState(params={"w": jnp.ones(D)}, step=0)
        st.commit()
        assert not hasattr(st, "_weights_publisher")

    def test_publish_failure_never_kills_training(self, tmp_path,
                                                  monkeypatch):
        import jax.numpy as jnp
        from horovod_tpu.elastic.state import JaxState
        wdir = str(tmp_path / "w")
        monkeypatch.setenv("HOROVOD_WEIGHTS_DIR", wdir)
        monkeypatch.setenv("HOROVOD_WEIGHTS_PUBLISH_EVERY", "1")
        faults.configure("weights.publish:error:at=1", seed=1)
        st = JaxState(params={"w": jnp.ones(D)}, step=0)
        st.commit()                      # publish fails; commit wins
        faults.configure("", seed=0)
        assert W._read_current(wdir) is None
        st.step = 1
        st.commit()                      # retry on the next cadence
        assert W._read_current(wdir) is not None

    def test_maybe_repair_recovers_torn_current(self, tmp_path,
                                                monkeypatch):
        wdir = str(tmp_path / "w")
        pub = W.WeightPublisher(wdir)
        v1 = pub.publish(_params(1.0), 1)
        faults.configure("weights.publish:torn:at=1", seed=1)
        pub.publish(_params(2.0), 2)
        faults.configure("", seed=0)
        monkeypatch.setenv("HOROVOD_WEIGHTS_DIR", wdir)
        W.maybe_repair()
        cur = W._read_current(wdir)
        assert cur.digest == v1.digest
        W.load_named(wdir, cur)          # verifies intact


# -- journal registration: new typed events, old readers -------------------


class TestJournalRegistration:
    def test_weights_events_are_critical(self):
        assert {"weights_published", "weights_adopted",
                "weights_rejected"} <= journal.CRITICAL_EVENTS

    def test_timeline_carries_weights_events(self, tmp_path,
                                             monkeypatch):
        jdir = tmp_path / "journal"
        jdir.mkdir()
        monkeypatch.setenv("HOROVOD_JOURNAL_DIR", str(jdir))
        journal.configure("worker", rank=0)
        journal.record("weights_published", digest="d1", seq=1,
                       step=10, kind="publish", ms=1.0)
        journal.record("weights_rejected", worker="w0", digest="d1",
                       seq=1, reason="torn", detail="x",
                       serving="d0")
        journal.record("weights_adopted", worker="w0", digest="d1",
                       seq=2, step=10, ms=2.0, staleness_steps=0)
        journal._journal.close()
        journal._journal = None
        _, report = journal.write_incident_report(str(jdir))
        # timeline rows are [t_rel, who, type, detail]
        types = [e[2] for e in report["timeline"]]
        assert types.count("weights_published") == 1
        assert types.count("weights_adopted") == 1
        assert types.count("weights_rejected") == 1

    def test_old_incident_artifacts_unaffected(self, tmp_path):
        """The new event types must not perturb regeneration of the
        committed r11/r14 incident artifacts (their journals contain
        no weights events) — the byte-identity pins live in
        test_journal.py / test_slices.py; here we pin the keep-set
        semantics they rely on: unknown-to-old-readers event types
        outside the keep-set still do not leak into timelines."""
        entries = journal._timeline_entries(
            [{"type": "weights_published", "t": 1.0, "n": 1,
              "role": "worker", "digest": "d"},
             {"type": "not_a_real_event", "t": 2.0, "n": 2,
              "role": "worker"}], 0.0)
        assert [e[2] for e in entries] == ["weights_published"]


# -- committed bench artifact pins -----------------------------------------


class TestWeightSwapBenchArtifact:
    def test_artifact_pins(self):
        doc = json.load(open(BENCH_ARTIFACT))
        swap = doc["rolling_update"]
        # zero-downtime: nothing dropped, nothing failed, across
        # every leg of the rolling update
        assert swap["dropped"] == 0 and swap["failed"] == 0
        assert swap["swaps"] >= 1
        # epoch fence witnessed in the trace: every served batch
        # carries exactly one digest from the published set
        assert swap["fence"]["mixed_version_batches"] == 0
        assert swap["fence"]["digests_seen"] >= 2
        # p99 during the swap window stays inside the SLO budget
        assert 0 < swap["p99_during_swap_ms"] <= \
            doc["config"]["slo_budget_ms"]
        assert swap["swap_ms"]["max"] >= swap["swap_ms"]["mean"] > 0
        chaos = doc["chaos"]
        assert chaos["dropped"] == 0 and chaos["failed"] == 0
        assert chaos["worker_deaths"] >= 1
        assert chaos["corrupt_rejections"] >= 1
        assert chaos["converged_digest"] == chaos["final_digest"]
        rb = doc["rollback"]
        assert rb["restored_digest"] == rb["previous_digest"]
        assert rb["dropped"] == 0
        stale = doc["staleness_curve"]
        assert stale and stale[-1]["staleness_steps"] == 0

    def test_trajectory_row_matches_artifact(self):
        traj = json.load(open(TRAJECTORY))
        row = traj["r17_weightswap"]
        doc = json.load(open(BENCH_ARTIFACT))
        assert row["p99_during_swap_ms"] == \
            doc["rolling_update"]["p99_during_swap_ms"]
        assert row["swap_mean_ms"] == \
            doc["rolling_update"]["swap_ms"]["mean"]
        assert row["mixed_version_batches"] == 0
        assert row["source"] == "benchmarks/BENCH_weightswap_r17.json"

    @pytest.mark.integration
    def test_trajectory_regenerates_byte_identical(self, tmp_path):
        """--trajectory is a pure function of the committed
        artifacts: regenerating with the r17 row wired in must
        reproduce the committed bytes exactly."""
        out = tmp_path / "traj.json"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_TRAJECTORY_OUT"] = str(out)
        subprocess.run(
            [sys.executable, "bench.py", "--trajectory"],
            cwd=REPO, env=env, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        assert out.read_bytes() == \
            open(TRAJECTORY, "rb").read()
