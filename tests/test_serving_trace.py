"""Serving request-lifecycle tracing tests: phase-stamp monotonicity
on the happy path, retry-hop linkage under an injected mid-batch
worker death, SLO goodput counting (hit / late / failed), the
disarmed fast path (HOROVOD_SERVING_TRACE=0 leaves no trace state and
the submit seam stays one load+compare), the postmortem in-flight
provider, `doctor serve` byte-determinism + torn-file tolerance + the
CLI exit contract, and the committed r16 attribution artifact's pins
(byte-identical regeneration from the committed trace recording via
both the library and `bench.py --serving-attribution`)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import faults, journal, serving_trace, tracing
from horovod_tpu.runner import doctor
from horovod_tpu.serving import ServingError, ServingFrontend
from horovod_tpu.serving import PHASES as LIVE_PHASES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORD_DIR = os.path.join(REPO, "benchmarks", "serving_trace_r16")
ATTRIBUTION = os.path.join(REPO, "benchmarks",
                           "SERVING_ATTRIBUTION_r16.json")
BENCH_SERVING = os.path.join(REPO, "benchmarks",
                             "BENCH_serving_r16.json")
TRAJECTORY = os.path.join(REPO, "benchmarks", "BENCH_trajectory.json")

D = 8  # feature width used by every frontend in this file

# The stamp order every winning hop must respect; phase p is the
# interval ending at EDGE[i+1] (see serving.PHASES).
EDGES = ("admit_ns", "claim_ns", "exec0_ns", "exec1_ns", "unpad_ns")


def _forward(x):
    import jax.numpy as jnp
    return jnp.tanh(x) * 2.0


def _expect(x):
    return np.tanh(np.asarray(x, dtype=np.float32)) * 2.0


@pytest.fixture(autouse=True)
def _clean_fault_and_journal_state():
    """Frontends (re)configure the module journal and tests arm the
    fault plan; restore both so state never leaks across tests."""
    yield
    faults.configure("", seed=0)
    if journal._journal is not None:
        journal._journal.close()
    journal._journal = None


def _base_env(tmp_path=None, **over):
    env = {
        "HOROVOD_SERVING_MAX_BATCH": "4",
        "HOROVOD_SERVING_LATENCY_BUDGET_MS": "5",
        "HOROVOD_SERVING_MIN_WORKERS": "1",
        "HOROVOD_SERVING_MAX_WORKERS": "4",
        "HOROVOD_SERVING_SCALE_INTERVAL_S": "0.05",
        "HOROVOD_SERVING_WORKER_TIMEOUT_S": "30",
    }
    if tmp_path is not None:
        jdir = os.path.join(str(tmp_path), "journal")
        os.makedirs(jdir, exist_ok=True)
        env["HOROVOD_JOURNAL_DIR"] = jdir
    env.update({k: str(v) for k, v in over.items()})
    return env


def _journal_events(tmp_path, role="serving"):
    path = os.path.join(str(tmp_path), "journal",
                        f"journal-{role}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _wait_journal_traces(tmp_path, n, role="serving"):
    """Poll until the journal's batch_trace events cover n requests.
    `result()` unblocks the submitter BEFORE the worker thread folds
    the batch's stamps into the trace log + journal, so readers must
    wait for the records, not the futures."""
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        evs = _journal_events(tmp_path, role)
        if sum(e["size"] for e in evs
               if e["type"] == "batch_trace") >= n:
            return evs
        time.sleep(0.01)
    pytest.fail(f"journal never reached {n} traced requests")


def _run_leg(tmp_path, n=8, workers=1, tag=None, slo_ms=None):
    """One traced serving leg: n requests through `workers` local
    workers, every result checked; returns the frontend's retained
    trace records and final stats."""
    env = _base_env(tmp_path)
    fe = ServingFrontend(_forward, (D,), env=env, start_pool=False,
                         autoscale=False, trace_tag=tag)
    try:
        fe.start_pool(workers)
        rng = np.random.RandomState(16)
        xs = [rng.randn(D).astype(np.float32) for _ in range(n)]
        futs = [fe.submit(x, slo_ms=slo_ms) for x in xs]
        for x, f in zip(xs, futs):
            np.testing.assert_allclose(f.result(timeout=60),
                                       _expect(x),
                                       rtol=1e-5, atol=1e-5)
        _wait_journal_traces(tmp_path, n,
                             role=f"serving-{tag}" if tag
                             else "serving")
        recs = fe.traces()
        stats = fe.stats()
    finally:
        fe.close()
    return recs, stats


# -- phase stamps ----------------------------------------------------------


class TestPhaseStamps:
    def test_phase_names_lockstep_with_offline_analyzer(self):
        """serving_trace.py duplicates PHASES to stay importable
        without jax; the two tuples must never drift."""
        assert LIVE_PHASES == serving_trace.PHASES

    def test_stamps_monotonic_and_phases_telescope(self, tmp_path):
        recs, stats = _run_leg(tmp_path, n=8)
        assert len(recs) == 8 and stats["dropped"] == 0
        for rec in recs:
            phases = rec["phases_ns"]
            assert set(phases) == set(LIVE_PHASES)
            assert all(d >= 0 for d in phases.values())
            # no retry: the stamps are taken in program order, so the
            # phases telescope exactly to the end-to-end latency
            assert sum(phases.values()) == \
                rec["t_done_ns"] - rec["t_submit_ns"], rec
            assert rec["hops"] and rec["hops"][-1][2] == "ok"
        evs = _journal_events(tmp_path)
        traces = [e for e in evs if e["type"] == "batch_trace"]
        assert traces and sum(e["size"] for e in traces) == 8
        for ev in traces:
            stamps = [int(ev[k]) for k in EDGES]
            assert stamps == sorted(stamps), ev
            for sub, done in zip(ev["submit_ns"], ev["done_ns"]):
                assert sub <= int(ev["admit_ns"])
                assert int(ev["unpad_ns"]) <= done

    def test_stats_carries_live_digest(self, tmp_path):
        recs, stats = _run_leg(tmp_path, n=6)
        dig = stats["trace"]
        assert dig["requests"] == 6
        for p in LIVE_PHASES:
            row = dig["phases"][p]
            assert row["n"] == 6
            assert 0 <= row["p50_ms"] <= row["p99_ms"]


# -- retry-hop linkage -----------------------------------------------------


class TestRetryHopLinkage:
    def test_mid_batch_kill_links_hops(self, tmp_path):
        """An injected worker death mid-batch must show up in the
        winning trace record as a CHAIN of hops — the killed attempt
        marked retried:<cause>, the survivor's marked ok — with the
        journal's batch_retried event naming the same batch."""
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(2)
            faults.configure("serving.batch:error:at=2", seed=0)
            rng = np.random.RandomState(3)
            xs = [rng.randn(D).astype(np.float32) for _ in range(12)]
            futs = [fe.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                np.testing.assert_allclose(f.result(timeout=60),
                                           _expect(x),
                                           rtol=1e-5, atol=1e-5)
            faults.configure("", seed=0)
            _wait_journal_traces(tmp_path, 12)
            recs = fe.traces()
            stats = fe.stats()
        finally:
            fe.close()
        assert stats["retries"] >= 1 and stats["dropped"] == 0
        retried = [r for r in recs if len(r["hops"]) >= 2]
        assert retried, "no trace record carries the retry chain"
        for rec in retried:
            assert rec["attempt"] >= 1
            outcomes = [h[2] for h in rec["hops"]]
            assert outcomes[-1] == "ok"
            assert any(o.startswith("retried:fault_error")
                       for o in outcomes[:-1]), outcomes
            # hop stamps: each hop is claimed after its predecessor
            claims = [h[3] for h in rec["hops"]]
            assert claims == sorted(claims)
        evs = _journal_events(tmp_path)
        jr = [e for e in evs if e["type"] == "batch_retried"]
        assert jr and jr[0]["batch"] in {r["batch"] for r in retried}
        # the journaled batch_trace for the retried batch carries the
        # full hop list too (doctor serve rebuilds chains from it)
        jt = [e for e in evs if e["type"] == "batch_trace"
              and e["batch"] == jr[0]["batch"]]
        assert jt and len(jt[0]["hops"]) >= 2


# -- SLO goodput -----------------------------------------------------------


class TestSloGoodput:
    def test_generous_slo_counts_hit(self, tmp_path):
        recs, stats = _run_leg(tmp_path, n=4, slo_ms=60000)
        assert all(r["slo"] == "60000ms" and r["outcome"] == "ok"
                   for r in recs)
        assert stats["trace"]["goodput"]["60000ms"] == \
            {"hit": 4, "late": 0, "failed": 0}

    def test_impossible_slo_counts_late(self, tmp_path):
        recs, stats = _run_leg(tmp_path, n=4, slo_ms=0.001)
        assert all(r["slo"] == "0.001ms" and r["outcome"] == "late"
                   for r in recs)
        assert stats["trace"]["goodput"]["0.001ms"]["late"] == 4

    def test_retry_exhaustion_counts_failed(self, tmp_path):
        """A visibly-failed request lands in the journal's
        batch_failed event with its SLO class, and doctor serve folds
        it into the goodput table's `failed` column."""
        env = _base_env(tmp_path, HOROVOD_SERVING_RETRY_LIMIT="1",
                        HOROVOD_SERVING_SCALE_INTERVAL_S="0.02")
        # autoscale on: each injected death empties the pool, and the
        # floor-restore is what re-dispatches the doomed batch
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=True)
        try:
            fe.start_pool(1)
            ok = fe.submit(np.ones(D, np.float32), slo_ms=60000)
            ok.result(timeout=60)
            _wait_journal_traces(tmp_path, 1)
            faults.configure("serving.batch:error", seed=0)
            doomed = fe.submit(np.ones(D, np.float32), slo_ms=60000)
            with pytest.raises(ServingError):
                doomed.result(timeout=60)
            faults.configure("", seed=0)
        finally:
            faults.configure("", seed=0)
            fe.close()
        evs = _journal_events(tmp_path)
        failed = [e for e in evs if e["type"] == "batch_failed"]
        assert failed and failed[0]["slo"] == ["60000ms"]
        assert failed[0]["lost"] == 1 and len(failed[0]["hops"]) >= 2
        report = serving_trace.serving_report(
            os.path.join(str(tmp_path), "journal"))
        good = report["legs"][0]["goodput"]["60000ms"]
        assert good["hit"] == 1 and good["failed"] == 1


# -- disarmed fast path ----------------------------------------------------


class TestDisarmedFastPath:
    def test_trace_off_leaves_no_state(self, tmp_path):
        ring_before = sum(1 for e in tracing.ring_events()
                          if str(e[1]).startswith("serving_"))
        env = _base_env(tmp_path, HOROVOD_SERVING_TRACE="0")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            fe.start_pool(1)
            futs = [fe.submit(np.ones(D, np.float32))
                    for _ in range(8)]
            for f in futs:
                f.result(timeout=60)
            assert fe.traces() == []
            stats = fe.stats()
        finally:
            fe.close()
        assert "trace" not in stats
        assert not [e for e in _journal_events(tmp_path)
                    if e["type"] == "batch_trace"]
        ring_after = sum(1 for e in tracing.ring_events()
                         if str(e[1]).startswith("serving_"))
        assert ring_after == ring_before

    def test_disarmed_seam_overhead(self, tmp_path):
        """Same shape as the faults/metrics fast-path guards: with
        tracing off, every seam on the submit/dispatch/completion
        path is one instance-attribute load + compare
        (`if self._trace:`). Generous bound for a loaded CI host."""
        env = _base_env(tmp_path, HOROVOD_SERVING_TRACE="0")
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False)
        try:
            assert fe._trace is False
            n = 50000
            t0 = time.perf_counter()
            for _ in range(n):
                if fe._trace:
                    pytest.fail("trace armed")
            per_call = (time.perf_counter() - t0) / n
        finally:
            fe.close()
        assert per_call < 20e-6, f"{per_call * 1e6:.2f} us/call"


# -- postmortem in-flight provider -----------------------------------------


class TestPostmortemProvider:
    def test_dump_carries_inflight_requests(self, tmp_path):
        """A postmortem dump (the SIGKILL story) must list each live
        frontend's queued request ids and in-flight batches with the
        last completed phase — state the in-memory trace log cannot
        tell because it dies with the process."""
        env = _base_env(tmp_path)
        fe = ServingFrontend(_forward, (D,), env=env,
                             start_pool=False, autoscale=False,
                             trace_tag="pm-test")
        try:
            ids = [fe.submit(np.ones(D, np.float32)).id
                   for _ in range(3)]
            deadline = time.monotonic() + 5
            while fe.admitted == 0 and time.monotonic() < deadline:
                time.sleep(0.01)  # let the batcher cut (5 ms budget)
            path = tracing.write_postmortem(
                "unit test", trigger="manual",
                path=os.path.join(str(tmp_path), "pm.json"))
            assert path is not None
            with open(path) as f:
                doc = json.load(f)
            tables = [t for t in doc["serving"]
                      if t["tag"] == "pm-test"]
            assert tables, doc.get("serving")
            tab = tables[0]
            listed = set(tab["queued"])
            for b in tab["batches"]:
                # never claimed (no workers): stuck before dispatch
                assert b["last_phase"] == "queued"
                assert b["pending"] == len(b["requests"])
                listed.update(b["requests"])
            assert listed == set(ids)
        finally:
            fe.close(timeout=0.2)  # no workers: fail the stragglers


# -- doctor serve ----------------------------------------------------------


def _recorded_run(tmp_path, tag="det"):
    """A traced leg recorded the way bench.py records: journals under
    <tmp>/journal plus the frontend's Chrome-trace timeline sitting
    next to them. Returns the journal dir."""
    env = _base_env(tmp_path)
    jdir = env["HOROVOD_JOURNAL_DIR"]
    fe = ServingFrontend(_forward, (D,), env=env, start_pool=False,
                         autoscale=False, trace_tag=tag)
    try:
        fe.start_pool(1)
        futs = [fe.submit(np.ones(D, np.float32)) for _ in range(6)]
        for f in futs:
            f.result(timeout=60)
        _wait_journal_traces(tmp_path, 6, role=f"serving-{tag}")
        fe.write_timeline(os.path.join(jdir,
                                       f"serving-{tag}.trace.json"))
    finally:
        fe.close()
    journal._journal.close()
    journal._journal = None
    return jdir


class TestDoctorServe:
    def test_report_byte_determinism(self, tmp_path):
        d = _recorded_run(tmp_path)
        p1, _ = serving_trace.write_serving_report(
            d, out=os.path.join(str(tmp_path), "r1.json"))
        p2, _ = serving_trace.write_serving_report(
            d, out=os.path.join(str(tmp_path), "r2.json"))
        b1 = open(p1, "rb").read()
        assert b1 == open(p2, "rb").read()
        raw = b1.decode()
        # incident-report protocol: no environment-dependent content
        assert str(tmp_path) not in raw
        assert "unix_time" not in raw
        report = json.loads(raw)
        (leg,) = report["legs"]
        assert leg["tag"] == "det" and leg["requests"] == 6
        assert leg["workers"] == ["w0"]
        assert report["timelines"][0]["file"] == \
            "serving-det.trace.json"
        assert report["timelines"][0]["spans"] >= 6
        assert report["timelines"][0]["torn"] is False

    def test_torn_files_tolerated(self, tmp_path):
        """A SIGKILL mid-write leaves a torn journal tail and an
        unclosed trace.json; the analyzer must fold every complete
        line and say what it repaired."""
        d = _recorded_run(tmp_path, tag="torn")
        (jpath,) = [os.path.join(d, f) for f in os.listdir(d)
                    if f.startswith("journal-")]
        with open(jpath, "a") as f:
            f.write('{"type": "batch_tr')  # torn mid-record
        tpath = os.path.join(d, "serving-torn.trace.json")
        data = open(tpath, "rb").read()
        with open(tpath, "wb") as f:
            f.write(data[:len(data) * 2 // 3])  # no closing bracket
        report = serving_trace.serving_report(d)
        (src,) = report["sources"]
        assert src["repaired_tail_lines"] >= 1
        (tl,) = report["timelines"]
        assert tl["torn"] is True and tl["spans"] >= 1
        assert report["legs"][0]["requests"] == 6

    def test_cli_exit_contract(self, tmp_path, capsys):
        d = _recorded_run(tmp_path)
        assert doctor.main(["serve", d]) == 0
        out = capsys.readouterr().out
        assert "report:" in out and "leg serving-det" in out
        assert os.path.exists(os.path.join(d, "serving_report.json"))
        # a dir with no journals is a clean failure, not a traceback
        empty = os.path.join(str(tmp_path), "empty")
        os.makedirs(empty)
        assert doctor.main(["serve", empty]) == 1
        assert "doctor serve:" in capsys.readouterr().out
        assert doctor.main(
            ["serve", os.path.join(str(tmp_path), "nope")]) == 1
        assert "doctor serve:" in capsys.readouterr().out


# -- committed r16 artifacts -----------------------------------------------


class TestCommittedAttribution:
    """The acceptance pin: SERVING_ATTRIBUTION_r16.json regenerates
    byte-identically from the committed trace recording
    (benchmarks/serving_trace_r16/) via BOTH the analyzer library and
    `bench.py --serving-attribution`, and names the dominant phase of
    the 1->2-worker scale-out regression with its measured share."""

    def test_regenerates_byte_identically(self, tmp_path):
        out = os.path.join(str(tmp_path), "regen.json")
        serving_trace.write_serving_report(RECORD_DIR, out=out)
        want = open(ATTRIBUTION, "rb").read()
        assert open(out, "rb").read() == want
        # the recording's in-dir report is the same bytes too
        assert open(os.path.join(RECORD_DIR, "serving_report.json"),
                    "rb").read() == want

    @pytest.mark.integration
    def test_bench_cli_regenerates_byte_identically(self, tmp_path):
        out = os.path.join(str(tmp_path), "attr.json")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env["BENCH_SERVING_ATTRIBUTION_OUT"] = out
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--serving-attribution"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert open(out, "rb").read() == \
            open(ATTRIBUTION, "rb").read()
        last = json.loads(r.stdout.strip().splitlines()[-1])
        assert last["metric"] == "serving_attribution_dominant_share"
        assert last["value"] >= 0.5

    def test_attribution_acceptance(self):
        report = json.load(open(ATTRIBUTION))
        assert report["schema"] == serving_trace.REPORT_SCHEMA
        attr = report["attribution"]
        assert attr["base_leg"] == "serving-w1"
        assert attr["scaled_leg"] == "serving-w2"
        # the measured answer to ROADMAP item 2: the single-threaded
        # admission loop, not compute, pays for the second worker
        assert attr["dominant_phase"] == "batch_cut"
        assert attr["dominant_share"] >= 0.5
        assert len(attr["top2"]) == 2
        shares = [p["share"] for p in attr["by_phase"].values()
                  if p["share"] > 0]
        assert sum(shares) == pytest.approx(1.0, abs=0.01)
        # shares are of the phase-level regression, which stays
        # well-defined even when extra drain capacity hides the
        # end-to-end delta
        assert attr["regression_ms"] > 0
        legs = {leg["role"]: leg for leg in report["legs"]}
        assert set(legs) == {"serving-w1", "serving-w2"}
        assert len(legs["serving-w2"]["workers"]) == 2
        for leg in legs.values():
            assert leg["requests"] == 256

    def test_bench_serving_doc_pins(self):
        doc = json.load(open(BENCH_SERVING))
        attr = json.load(open(ATTRIBUTION))["attribution"]
        assert doc["attribution"]["dominant_phase"] == \
            attr["dominant_phase"]
        assert doc["attribution"]["dominant_share"] == \
            attr["dominant_share"]
        assert doc["retry"]["dropped"] == 0
        for leg in ("workers1", "workers2"):
            trace = doc["serving_trace"][leg]
            assert trace["requests"] == 256
            assert set(trace["phases"]) == set(LIVE_PHASES)

    def test_trajectory_row(self):
        traj = json.load(open(TRAJECTORY))
        row = traj["r16_serving_attribution"]
        attr = json.load(open(ATTRIBUTION))["attribution"]
        assert row["dominant_phase"] == attr["dominant_phase"]
        assert row["dominant_share"] == attr["dominant_share"]
        assert row["added_mean_ms_1to2_workers"] == \
            attr["added_mean_ms"]
        assert row["source"] == "benchmarks/SERVING_ATTRIBUTION_r16.json"
