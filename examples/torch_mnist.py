#!/usr/bin/env python
"""The reference's canonical torch example, unchanged in spirit
(reference: examples/pytorch/pytorch_mnist.py) — running on the
torch frontend binding: `import horovod_tpu.torch as hvd` is the
only import that differs from the reference script.

Demonstrates the full migration surface: DistributedOptimizer with
named_parameters (hook-based overlap), broadcast_parameters +
broadcast_optimizer_state on start, rank-sharded data, and metric
averaging via allreduce. Synthetic MNIST-shaped data keeps it
self-contained (no downloads).

  python examples/torch_mnist.py --epochs 2
  python -m horovod_tpu.runner -np 2 python examples/torch_mnist.py
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(torch.relu(self.fc1(x.reshape(-1, 784))))


def synthetic_mnist(n, seed):
    """Linearly separable digit-shaped data so accuracy is a real
    convergence signal."""
    g = torch.Generator().manual_seed(seed)
    proto = torch.randn(10, 784, generator=g)
    labels = torch.randint(0, 10, (n,), generator=g)
    imgs = proto[labels] + 0.3 * torch.randn(n, 784, generator=g)
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(42)   # identical init everywhere; broadcast
    model = Net()           # below makes it bitwise so anyway
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    # reference: lr scales with world size under the linear rule
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size(),
                        momentum=0.9),
        named_parameters=model.named_parameters())
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    # rank-sharded data (reference: DistributedSampler)
    X, Y = synthetic_mnist(4096, seed=0)
    X = X[hvd.rank()::hvd.size()]
    Y = Y[hvd.rank()::hvd.size()]

    for epoch in range(args.epochs):
        perm = torch.randperm(len(X))
        correct = total = 0
        for i in range(0, len(X), args.batch_size):
            idx = perm[i:i + args.batch_size]
            xb, yb = X[idx], Y[idx]
            opt.zero_grad()
            out = model(xb)
            loss = F.cross_entropy(out, yb)
            loss.backward()
            opt.step()
            correct += int((out.argmax(1) == yb).sum())
            total += len(yb)
        # metric averaging across ranks (reference: metric_average)
        acc = hvd.allreduce(torch.tensor([correct / total]),
                            name=f"acc.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: train accuracy {float(acc[0]):.4f}")
    if hvd.rank() == 0:
        print(f"final train accuracy: {float(acc[0]):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
