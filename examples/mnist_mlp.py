#!/usr/bin/env python
"""BASELINE config 1: the MNIST correctness harness — the reference's
5-line experience (reference: examples/pytorch/pytorch_mnist.py),
TPU-native.

Run:  python -m horovod_tpu.runner -np 2 python examples/mnist_mlp.py
(synthetic MNIST-shaped data so the example runs with zero downloads;
point --data at an .npz with x_train/y_train to use real MNIST)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import init_mlp, mlp_forward, mlp_loss_fn


def load_data(path, n=4096):
    if path and os.path.exists(path):
        d = np.load(path)
        return d["x_train"].reshape(-1, 784) / 255.0, d["y_train"]
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 784), dtype=np.float32)
    w = rng.standard_normal((784, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1)  # learnable synthetic labels
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--data", default=None)
    args = ap.parse_args()

    # 1. initialize
    hvd.init()
    x, y = load_data(args.data)

    # 2. shard the data by rank
    n_local = len(x) // hvd.size()
    lo = hvd.rank() * n_local
    x, y = x[lo:lo + n_local], y[lo:lo + n_local]

    params = init_mlp(jax.random.PRNGKey(0))
    # 3. broadcast initial parameters from rank 0
    params = hvd.broadcast_parameters(params, root_rank=0)
    # 4. wrap the optimizer (lr scaled by world size, as the
    #    reference's examples do)
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr * hvd.size()))
    opt_state = opt.init(params)

    grad_fn = jax.jit(jax.value_and_grad(mlp_loss_fn))

    steps = n_local // args.batch_size
    for epoch in range(args.epochs):
        for i in range(steps):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            batch = {"images": jnp.asarray(x[sl]),
                     "labels": jnp.asarray(y[sl])}
            loss, grads = grad_fn(params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        # 5. average the metric across workers
        avg = hvd.allreduce(jnp.asarray([float(loss)]),
                            name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(avg[0]):.4f}")

    logits = mlp_forward(params, jnp.asarray(x[:512]))
    acc = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y[:512])))
    acc = float(hvd.allreduce(jnp.asarray([acc]), name="acc")[0])
    if hvd.rank() == 0:
        print(f"final train accuracy: {acc:.3f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
