#!/usr/bin/env python
"""BASELINE config 4: Llama-2-7B data-parallel — Adasum + gradient
compression (reference: the Llama config in BASELINE.md).

Llama-2-7B dimensions (32 layers, d=4096, 32 heads, d_ff=11008,
vocab 32000) with --full; smoke-sized by default. Demonstrates:
  * op=hvd.Adasum — adaptive summation (reference:
    horovod/common/ops/adasum/, arXiv:2006.02924) as the gradient
    combine, implemented with recursive halving-doubling in pure JAX
    over XLA collectives
  * Compression.fp16 on the wire
  * optional tensor parallelism on top (--tp N) via the flagship
    SPMD path — something the reference cannot do at all.

  python -m horovod_tpu.runner -np 2 python examples/llama2_7b_dp.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.ops.compression import Compression


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=128)
    args = ap.parse_args()

    hvd.init()
    if args.full:
        cfg = tfm.TransformerConfig(
            vocab=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, head_dim=128, d_ff=11008,
            max_seq=args.seq_len, dtype=jnp.bfloat16,
            tp_axis=None, sp_axis=None, ep_axis=None)
    else:
        cfg = tfm.TransformerConfig(
            vocab=512, d_model=128, n_layers=4, n_heads=8,
            n_kv_heads=4, head_dim=16, d_ff=384, max_seq=args.seq_len,
            dtype=jnp.float32, tp_axis=None, sp_axis=None,
            ep_axis=None)

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    params = hvd.broadcast_parameters(params, root_rank=0)

    opt = hvd.DistributedOptimizer(
        optax.adamw(3e-4),
        op=hvd.Adasum,               # adaptive summation
        compression=Compression.fp16)
    opt_state = opt.init(params)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, b: tfm.loss_fn(cfg, p, b)))

    key = jax.random.PRNGKey(hvd.rank())
    for step in range(args.steps):
        key, k = jax.random.split(key)
        tokens = jax.random.randint(
            k, (args.batch_size, args.seq_len), 0, cfg.vocab,
            jnp.int32)
        batch = {"tokens": tokens,
                 "targets": jnp.roll(tokens, -1, axis=1)}
        loss, grads = grad_fn(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if hvd.rank() == 0:
            print(f"step {step}: loss {float(loss):.3f} (Adasum+fp16)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
