#!/usr/bin/env python
"""BASELINE config 5: elastic ResNet-50 — dynamic worker add/remove
(reference: horovod.elastic ResNet; docs/elastic.rst pattern).

  python -m horovod_tpu.runner \\
      --host-discovery-script ./discover.sh --min-num-proc 1 \\
      python examples/elastic_resnet50.py

where discover.sh prints "host:slots" lines and may change over time.
Commits every batch; resizes reshard the remaining data via
ElasticSampler; hard failures resume from the rank-0 snapshot.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.resnet import create_resnet50, init_resnet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches-per-epoch", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--snapshot", default="/tmp/elastic_resnet.snap")
    args = ap.parse_args()

    hvd.init()
    model = create_resnet50(num_classes=100, dtype=jnp.float32)
    variables = init_resnet(model, jax.random.PRNGKey(0),
                            args.image_size)
    opt = optax.sgd(0.01 * hvd.size(), momentum=0.9)

    state = hvd.elastic.JaxState(
        params=variables["params"],
        opt_state=opt.init(variables["params"]),
        batch_stats=variables["batch_stats"],
        epoch=0, batch_idx=0,
        snapshot_path=args.snapshot)
    state._tree_attrs.append("batch_stats")

    def loss_fn(params, stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": stats}, images,
            train=True, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        loss = jnp.mean(
            -jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, updates["batch_stats"]

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

    @hvd.elastic.run
    def train(state):
        opt_d = hvd.DistributedOptimizer(opt)
        rng = np.random.default_rng(1234)
        while state.epoch < args.epochs:
            while state.batch_idx < args.batches_per_epoch:
                images = jnp.asarray(rng.standard_normal(
                    (args.batch_size, args.image_size,
                     args.image_size, 3), dtype=np.float32))
                labels = jnp.asarray(
                    rng.integers(0, 100, args.batch_size), jnp.int32)
                (loss, new_stats), grads = grad_fn(
                    state.params, state.batch_stats, images, labels)
                updates, state.opt_state = opt_d.update(
                    grads, state.opt_state, state.params)
                state.params = optax.apply_updates(state.params,
                                                   updates)
                state.batch_stats = new_stats
                state.batch_idx += 1
                if hvd.rank() == 0:
                    print(f"epoch {state.epoch} batch "
                          f"{state.batch_idx} world {hvd.size()} "
                          f"loss {float(loss):.3f}", flush=True)
                state.commit()
            state.batch_idx = 0
            state.epoch += 1
            state.commit()

    train(state)
    if hvd.rank() == 0:
        print("elastic training complete")
    hvd.shutdown()


if __name__ == "__main__":
    main()
