#!/usr/bin/env python
"""BASELINE config 3: BERT-Large-class pretraining — fp16 gradients +
tensor-fusion stress (reference: the BERT config in BASELINE.md; the
reference exercises this through Keras + grouped allreduce of ~400
parameter tensors).

BERT-Large dimensions (24 layers, d=1024, 16 heads, d_ff=4096,
~340M params) with --full; the default is a smoke-sized model so the
example runs anywhere. The transformer here is this framework's
flagship (decoder mask off ≈ bidirectional encoder compute profile —
identical allreduce/fusion stress).

The training step is the EAGER hook-style path on purpose: hundreds of
per-parameter allreduce_async submissions with fp16 compression, all
fused by the negotiation core — exactly the reference's mechanism.

  python -m horovod_tpu.runner -np 2 python examples/bert_large_pretraining.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.callbacks import (BroadcastParametersCallback,
                                   CallbackContext, CallbackList,
                                   LearningRateWarmupCallback,
                                   MetricAverageCallback,
                                   lr_scale_schedule)
from horovod_tpu.models import transformer as tfm
from horovod_tpu.ops.compression import Compression


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="real BERT-Large dimensions")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps", type=int, default=3,
                    help="steps per epoch")
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--warmup-epochs", type=int, default=1)
    ap.add_argument("--num-groups", type=int, default=0,
                    help="explicit fusion group count (0 = one "
                         "grouped submission; the negotiation core "
                         "re-buckets by HOROVOD_FUSION_THRESHOLD)")
    args = ap.parse_args()
    if args.steps < 1 or args.epochs < 1:
        ap.error("--steps and --epochs must be >= 1")

    hvd.init()
    if args.full:
        cfg = tfm.TransformerConfig(
            vocab=30528, d_model=1024, n_layers=24, n_heads=16,
            n_kv_heads=16, head_dim=64, d_ff=4096,
            max_seq=args.seq_len, dtype=jnp.bfloat16,
            tp_axis=None, sp_axis=None, ep_axis=None)
    else:
        cfg = tfm.TransformerConfig(
            vocab=512, d_model=128, n_layers=4, n_heads=8,
            n_kv_heads=8, head_dim=16, d_ff=512, max_seq=args.seq_len,
            dtype=jnp.float32, tp_axis=None, sp_axis=None,
            ep_axis=None)

    params = tfm.init_params(cfg, jax.random.PRNGKey(hvd.rank()))

    # Reference-style callback-driven loop (reference:
    # horovod/_keras/callbacks.py usage in the BERT config): single-
    # worker base lr; the warmup callback ramps lr_scale to size over
    # --warmup-epochs; the broadcast callback makes initialization
    # consistent (params were deliberately seeded per-rank above);
    # metric averaging reduces the epoch loss across ranks.
    ctx = CallbackContext(params=params)
    cbs = CallbackList([
        BroadcastParametersCallback(root_rank=0),
        LearningRateWarmupCallback(warmup_epochs=args.warmup_epochs,
                                   verbose=True),
        MetricAverageCallback(),
    ])

    # fp16 gradient compression + grouped fusion: the config's point.
    # LR = eager schedule reading the callback-controlled scale.
    opt = hvd.DistributedOptimizer(
        optax.adamw(lr_scale_schedule(ctx, 1e-4)),
        compression=Compression.fp16,
        num_groups=args.num_groups)

    cbs.on_train_begin(ctx)          # broadcast initial params
    ctx.opt_state = opt.init(ctx.params)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, b: tfm.loss_fn(cfg, p, b)))

    key = jax.random.PRNGKey(hvd.rank())
    for epoch in range(args.epochs):
        cbs.on_epoch_begin(epoch, ctx)
        epoch_loss = 0.0
        for step in range(args.steps):
            key, k = jax.random.split(key)
            tokens = jax.random.randint(
                k, (args.batch_size, args.seq_len), 0, cfg.vocab,
                jnp.int32)
            batch = {"tokens": tokens,
                     "targets": jnp.roll(tokens, -1, axis=1)}
            loss, grads = grad_fn(ctx.params, batch)
            updates, ctx.opt_state = opt.update(
                grads, ctx.opt_state, ctx.params)
            ctx.params = optax.apply_updates(ctx.params, updates)
            epoch_loss += float(loss)
        metrics = cbs.on_epoch_end(
            epoch, {"loss": epoch_loss / args.steps}, ctx)
        if hvd.rank() == 0:
            n_tensors = len(jax.tree_util.tree_leaves(grads))
            print(f"epoch {epoch}: avg loss {metrics['loss']:.3f} "
                  f"lr_scale={ctx.lr_scale:.2f} "
                  f"({n_tensors} gradient tensors fused via fp16)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
