#!/usr/bin/env python
"""BASELINE config 3: BERT-Large-class pretraining — fp16 gradients +
tensor-fusion stress (reference: the BERT config in BASELINE.md; the
reference exercises this through Keras + grouped allreduce of ~400
parameter tensors).

BERT-Large dimensions (24 layers, d=1024, 16 heads, d_ff=4096,
~340M params) with --full; the default is a smoke-sized model so the
example runs anywhere. The transformer here is this framework's
flagship (decoder mask off ≈ bidirectional encoder compute profile —
identical allreduce/fusion stress).

The training step is the EAGER hook-style path on purpose: hundreds of
per-parameter allreduce_async submissions with fp16 compression, all
fused by the negotiation core — exactly the reference's mechanism.

  python -m horovod_tpu.runner -np 2 python examples/bert_large_pretraining.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.ops.compression import Compression


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="real BERT-Large dimensions")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--num-groups", type=int, default=0,
                    help="explicit fusion group count (0 = one "
                         "grouped submission; the negotiation core "
                         "re-buckets by HOROVOD_FUSION_THRESHOLD)")
    args = ap.parse_args()

    hvd.init()
    if args.full:
        cfg = tfm.TransformerConfig(
            vocab=30528, d_model=1024, n_layers=24, n_heads=16,
            n_kv_heads=16, head_dim=64, d_ff=4096,
            max_seq=args.seq_len, dtype=jnp.bfloat16,
            tp_axis=None, sp_axis=None, ep_axis=None)
    else:
        cfg = tfm.TransformerConfig(
            vocab=512, d_model=128, n_layers=4, n_heads=8,
            n_kv_heads=8, head_dim=16, d_ff=512, max_seq=args.seq_len,
            dtype=jnp.float32, tp_axis=None, sp_axis=None,
            ep_axis=None)

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    params = hvd.broadcast_parameters(params, root_rank=0)

    # fp16 gradient compression + grouped fusion: the config's point.
    opt = hvd.DistributedOptimizer(
        optax.adamw(1e-4 * hvd.size()),
        compression=Compression.fp16,
        num_groups=args.num_groups)
    opt_state = opt.init(params)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, b: tfm.loss_fn(cfg, p, b)))

    key = jax.random.PRNGKey(hvd.rank())
    for step in range(args.steps):
        key, k = jax.random.split(key)
        tokens = jax.random.randint(
            k, (args.batch_size, args.seq_len), 0, cfg.vocab,
            jnp.int32)
        batch = {"tokens": tokens,
                 "targets": jnp.roll(tokens, -1, axis=1)}
        loss, grads = grad_fn(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if hvd.rank() == 0:
            n_tensors = len(jax.tree_util.tree_leaves(grads))
            print(f"step {step}: loss {float(loss):.3f} "
                  f"({n_tensors} gradient tensors fused via fp16)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
