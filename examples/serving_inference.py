#!/usr/bin/env python
"""Beyond-reference: elastic inference serving
(`horovod_tpu/serving.py`) — the training stack's ingredients (AOT
compilation, elastic membership, fault seams, the lifecycle journal)
composed into a request-serving tier.

Run (single process, local thread pool over the host's devices):

    python examples/serving_inference.py

What it demonstrates:
  1. dynamic batching under a latency budget (requests arrive one by
     one; the frontend cuts batches at HOROVOD_SERVING_MAX_BATCH or
     when the oldest request's wait hits the budget);
  2. the padded-bucket no-recompile pin: mixed request lengths all
     land on the deterministic, digest-pinned BucketLadder shapes the
     workers AOT-compiled at warmup — the compile count must not
     grow under traffic;
  3. queue-depth autoscaling between the MIN/MAX worker knobs;
  4. exactly-once completion under an injected mid-batch worker
     death (`serving.batch` fault seam): the batch retries on a
     survivor and zero requests are dropped.

For a REMOTE pool (each worker its own process, pulling batches over
the HMAC-signed control-plane wire — the deployment shape), see the
`serve_endpoint()` / `remote_worker_loop()` pair in the user guide's
"Elastic inference serving" section and tests/serving_chaos_worker.py
for the elastic-runner worker script.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from horovod_tpu import faults
from horovod_tpu.serving import ServingFrontend

D_MODEL = 128


def make_forward():
    rng = np.random.RandomState(0)
    w1 = jnp.asarray(rng.randn(D_MODEL, 4 * D_MODEL) * 0.05,
                     jnp.float32)
    w2 = jnp.asarray(rng.randn(4 * D_MODEL, D_MODEL) * 0.05,
                     jnp.float32)

    def forward(x):
        return jnp.tanh(x @ w1) @ w2

    return forward


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--qps", type=float, default=400.0)
    ap.add_argument("--chaos", action="store_true",
                    help="inject a mid-run worker death via the "
                         "serving.batch fault seam")
    args = ap.parse_args()

    env = dict(os.environ)
    env.setdefault("HOROVOD_SERVING_MAX_BATCH", "8")
    env.setdefault("HOROVOD_SERVING_LATENCY_BUDGET_MS", "5")
    env.setdefault("HOROVOD_SERVING_MAX_LEN", "64")
    env.setdefault("HOROVOD_SERVING_MIN_WORKERS", "1")
    env.setdefault("HOROVOD_SERVING_MAX_WORKERS", "4")
    env.setdefault("HOROVOD_SERVING_SCALE_INTERVAL_S", "0.05")

    fe = ServingFrontend(make_forward(), (D_MODEL,), env=env)
    print(f"serving: ladder {fe.ladder.digest} "
          f"({len(fe.ladder.shapes((D_MODEL,)))} executable shapes)")

    if args.chaos:
        # Kill whichever worker pulls the 5th batch, mid-batch. The
        # frontend requeues its work on a survivor; the retry is
        # journaled and counted — and nothing is dropped.
        faults.configure("serving.batch:error:at=5", seed=0)

    rng = np.random.RandomState(1)
    gap = 1.0 / args.qps if args.qps else 0.0
    futs = []
    t0 = time.perf_counter()
    for _ in range(args.requests):
        # variable-length requests (L, D_MODEL): each pads to its
        # ladder bucket, so none of them recompiles anything
        L = int(rng.randint(1, 65))
        futs.append(fe.submit(
            rng.randn(L, D_MODEL).astype(np.float32)))
        if gap:
            time.sleep(gap)
    for f in futs:
        f.result(timeout=60)
    wall = time.perf_counter() - t0

    if args.chaos:
        faults.configure("", seed=0)
    lats = sorted(1e3 * (f.t_done - f.t_submit) for f in futs)
    s = fe.stats()
    fe.close()

    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
    print(f"serving: {s['completed']}/{s['submitted']} completed in "
          f"{wall:.2f}s ({s['submitted'] / wall:.0f} req/s), "
          f"p50={p50:.1f}ms p99={p99:.1f}ms")
    print(f"serving: {s['batches']} batches, {s['compiles']} "
          f"compiles (pinned at warmup), peak workers beyond floor "
          f"via {s['scale_events']} scale events")
    print(f"serving: retries={s['retries']} "
          f"duplicates_suppressed={s['duplicates_suppressed']} "
          f"failed={s['failed']} dropped={s['dropped']}")
    assert s["dropped"] == 0, "serving dropped requests"
    if args.chaos:
        assert s["retries"] >= 1, "chaos run should have retried"
    print("serving: OK (zero dropped requests)")


if __name__ == "__main__":
    main()
