#!/usr/bin/env python
"""Pipelined eager training: the TPU-native max-throughput recipe.

Same 5-line shape as mnist_mlp.py, but the optimizer apply is FUSED
into the next step's grad program via `hvd.make_pipelined_step` —
on TPU, XLA programs execute serially, so a stand-alone apply program
cannot overlap its HBM traffic with compute; pipelined, it can. The
grouped allreduce still runs eagerly between the programs through the
negotiated controller (fusion, response cache, compression). This
pattern benches the 436M-param flagship transformer at 1.00x the jit
train step on a v5e chip (docs/benchmarks.md).

Run:  python -m horovod_tpu.runner -np 2 python examples/pipelined_mlp.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import init_mlp, mlp_forward, mlp_loss_fn


def load_data(n=4096):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 784), dtype=np.float32)
    w = rng.standard_normal((784, 10)).astype(np.float32)
    return x, np.argmax(x @ w, axis=1)  # learnable synthetic labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    hvd.init()
    x, y = load_data()
    n_local = len(x) // hvd.size()
    lo = hvd.rank() * n_local
    x, y = x[lo:lo + n_local], y[lo:lo + n_local]

    params = init_mlp(jax.random.PRNGKey(0))
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = optax.adam(args.lr * hvd.size())

    def loss_fn(p, batch):
        return mlp_loss_fn(p, batch)

    # bf16 wire: the TPU-native compression (free cast for bf16
    # models; halves multi-rank wire bytes for this f32 one).
    step = hvd.make_pipelined_step(loss_fn, opt, op=hvd.Average,
                                   compression=hvd.Compression.bf16)

    steps = n_local // args.batch_size
    if steps < 2:
        sys.exit(f"pipelined_mlp: need >= 2 batches per epoch to "
                 f"pipeline (got {steps} at batch size "
                 f"{args.batch_size} with {n_local} local rows); "
                 "lower --batch-size")
    batches = [{"images": jnp.asarray(x[i * args.batch_size:
                                        (i + 1) * args.batch_size]),
                "labels": jnp.asarray(y[i * args.batch_size:
                                        (i + 1) * args.batch_size])}
               for i in range(steps)]

    # init() consumes the first batch; loop from the second.
    state = step.init(params, opt.init(params), batches[0])
    for epoch in range(args.epochs):
        start = 1 if epoch == 0 else 0
        for b in batches[start:]:
            state, loss = step(state, b)
        avg = hvd.allreduce(jnp.asarray([float(loss)]),
                            name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(avg[0]):.4f}")
    params, _ = step.finalize(state)

    logits = mlp_forward(params, jnp.asarray(x[:512]))
    acc = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y[:512])))
    acc = float(hvd.allreduce(jnp.asarray([acc]), name="acc")[0])
    if hvd.rank() == 0:
        print(f"final train accuracy: {acc:.3f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
