#!/usr/bin/env python
"""BASELINE config 2: ResNet-50 synthetic benchmark — pure allreduce
throughput (reference: examples/pytorch/pytorch_synthetic_benchmark.py).

Single host: every local device joins the data mesh; on a pod, run one
process per host via the launcher and the mesh spans all chips. This
is the same code path bench.py measures.

  python examples/resnet50_synthetic.py --batch-size 128 --num-iters 30
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.resnet import create_resnet50, init_resnet
from horovod_tpu.parallel import build_train_step
from horovod_tpu.parallel.mesh import data_parallel_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-device batch (reference default: 32)")
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-warmup", type=int, default=3)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--fp32", action="store_true",
                    help="float32 compute instead of bfloat16")
    args = ap.parse_args()

    hvd.init()
    mesh = data_parallel_mesh()
    n = mesh.devices.size
    global_batch = args.batch_size * n

    model = create_resnet50(
        dtype=jnp.float32 if args.fp32 else jnp.bfloat16)
    variables = init_resnet(model, jax.random.PRNGKey(0),
                            args.image_size)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, batch):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch["batch_stats"]},
            batch["images"], train=True, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(batch["labels"], logits.shape[-1])
        loss = jnp.mean(
            -jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, updates["batch_stats"]

    opt = optax.sgd(0.0125 * n, momentum=0.9)
    opt_state = opt.init(params)
    step = build_train_step(
        loss_fn, opt, mesh,
        batch_spec={"images": P("data"), "labels": P("data"),
                    "batch_stats": P()},
        loss_has_aux=True, donate=True)

    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P("data"))
    images = jax.device_put(
        jnp.asarray(rng.standard_normal(
            (global_batch, args.image_size, args.image_size, 3),
            dtype=np.float32)), sh)
    labels = jax.device_put(
        jnp.asarray(rng.integers(0, 1000, global_batch), jnp.int32), sh)
    batch_stats = jax.device_put(
        batch_stats, NamedSharding(mesh, P()))

    def one(params, opt_state, batch_stats):
        b = {"images": images, "labels": labels,
             "batch_stats": batch_stats}
        params, opt_state, m = step(params, opt_state, b)
        return params, opt_state, m["aux"], m["loss"]

    for _ in range(args.num_warmup):
        params, opt_state, batch_stats, loss = one(params, opt_state,
                                                   batch_stats)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        params, opt_state, batch_stats, loss = one(params, opt_state,
                                                   batch_stats)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_sec = global_batch * args.num_iters / dt
    if hvd.rank() == 0:
        print(f"Model: ResNet50, batch {args.batch_size}/device, "
              f"{n} device(s)")
        print(f"Img/sec total: {img_sec:.1f}")
        print(f"Img/sec per device: {img_sec / n:.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
