#!/usr/bin/env python
"""Expert parallelism: a Switch-style top-1 routed MoE layer over an
`expert` mesh axis, with token blocks exchanged by `all_to_all`.

The reference ships the alltoall PRIMITIVE an MoE needs
(hvd.alltoall with splits; SURVEY.md §2.6 'Expert parallel: primitive
only') but no routed layer; this example runs the full thing: local
router → capacity-bounded dispatch → all_to_all over ICI → per-expert
FFN → return all_to_all → weighted combine, with the Switch
load-balancing auxiliary loss.

Run (CPU demo, 8 virtual devices = 8-way expert parallelism):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/moe_expert_parallel.py --experts 16
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from horovod_tpu.common.compat import shard_map
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import MeshSpec, build_mesh
from horovod_tpu.parallel.moe import moe_ffn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--experts", type=int, default=16,
                    help="total experts (sharded over the mesh)")
    ap.add_argument("--tokens", type=int, default=1024,
                    help="tokens PER DEVICE")
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--d-ff", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    ep = len(jax.devices())
    assert args.experts % ep == 0, \
        f"device count ({ep}) must divide --experts ({args.experts})"
    e_local = args.experts // ep
    mesh = build_mesh(MeshSpec(data=1, expert=ep))
    T, Dm, F = args.tokens, args.d_model, args.d_ff
    print(f"MoE: {args.experts} experts over {ep} devices "
          f"({e_local}/device), {T} tokens/device, d={Dm}, ff={F}")

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.standard_normal((ep * T, Dm), dtype=np.float32))
    router_w = jnp.asarray(
        rng.standard_normal((Dm, args.experts), dtype=np.float32) * 0.02)
    w_in = jnp.asarray(rng.standard_normal(
        (args.experts, Dm, F), dtype=np.float32) * 0.02)
    w_out = jnp.asarray(rng.standard_normal(
        (args.experts, F, Dm), dtype=np.float32) * 0.02)

    tok_sh = NamedSharding(mesh, P("expert"))        # tokens by device
    exp_sh = NamedSharding(mesh, P("expert"))        # experts by device
    rep_sh = NamedSharding(mesh, P())
    tokens = jax.device_put(tokens, tok_sh)
    router_w = jax.device_put(router_w, rep_sh)
    w_in = jax.device_put(w_in, exp_sh)
    w_out = jax.device_put(w_out, exp_sh)

    def fwd(t, r, wi, wo):
        out, aux = moe_ffn(t, r, wi, wo, axis_name="expert")
        # each device routes its own tokens: average the local
        # load-balance losses so the scalar is truly replicated
        return out, jax.lax.pmean(aux, "expert")

    step = jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(P("expert"), P(), P("expert"), P("expert")),
        out_specs=(P("expert"), P())))

    out, aux = step(tokens, router_w, w_in, w_out)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out, aux = step(tokens, router_w, w_in, w_out)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / args.steps
    print(f"moe step: {dt * 1e3:.1f} ms, aux load-balance loss "
          f"{float(aux):.3f} (1.0 = perfectly balanced)")
    assert out.shape == tokens.shape
    print("expert-parallel MoE OK")


if __name__ == "__main__":
    main()
