#!/usr/bin/env python
"""The flax-idiom 5-line experience (reference analog:
examples/keras/keras_mnist.py — the framework-native sugar path):
`hvd.flax.DistributedTrainState.create` wraps the optax
transformation with cross-worker reduction AND broadcasts
params/opt_state from the root in one call.

  python examples/flax_train_state.py --epochs 3
  python -m horovod_tpu.runner -np 2 python examples/flax_train_state.py
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(128)(x.reshape((x.shape[0], -1))))
        return nn.Dense(10)(x)


def synthetic_mnist(n, seed):
    rng = np.random.default_rng(seed)
    proto = rng.normal(size=(10, 784)).astype(np.float32)
    labels = rng.integers(0, 10, size=n)
    imgs = proto[labels] + 0.3 * rng.normal(size=(n, 784)
                                            ).astype(np.float32)
    return jnp.asarray(imgs), jnp.asarray(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    hvd.init()
    model = MLP()
    params = model.init(jax.random.PRNGKey(hvd.rank()),  # rank-seeded
                        jnp.zeros((1, 784)))["params"]   # on purpose:
    # create() broadcasts from rank 0, so the rank-different init
    # above is erased — the one-call version of the reference's
    # BroadcastGlobalVariablesCallback.
    state = hvd.flax.DistributedTrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.adam(args.lr * hvd.size()),
        compression=hvd.Compression.bf16)

    X, Y = synthetic_mnist(4096, seed=0)
    X = X[hvd.rank()::hvd.size()]
    Y = Y[hvd.rank()::hvd.size()]

    def loss_fn(params, xb, yb):
        logits = state.apply_fn({"params": params}, xb)
        onehot = jax.nn.one_hot(yb, 10)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits),
                                 axis=-1))

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    rng = np.random.default_rng(1 + hvd.rank())
    for epoch in range(args.epochs):
        perm = rng.permutation(len(X))
        correct = total = 0
        for i in range(0, len(X), args.batch_size):
            idx = perm[i:i + args.batch_size]
            xb, yb = X[idx], Y[idx]
            loss, grads = grad_fn(state.params, xb, yb)
            state = state.apply_gradients(grads=grads)
            pred = state.apply_fn({"params": state.params}, xb
                                  ).argmax(-1)
            correct += int((pred == yb).sum())
            total += len(yb)
        acc = hvd.allreduce(jnp.asarray([correct / total]),
                            name=f"acc.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: train accuracy {float(acc[0]):.4f}")
    if hvd.rank() == 0:
        print(f"final train accuracy: {float(acc[0]):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
