#!/usr/bin/env python
"""Long-context sequence parallelism: exact ring attention over a
`seq` mesh axis.

The reference has no sequence-parallel layer (SURVEY.md §5.7 — it
predates the long-context era); this example shows the capability the
TPU rebuild adds on top of the same collective substrate: each device
holds 1/sp of the sequence, K/V blocks rotate around the ring
(`ppermute` over ICI) while partial attention accumulates with exact
log-sum-exp merging — memory per device is O(L/sp), results are
bitwise-identical in math to full attention.

Run (CPU demo, 8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/ring_attention_long_context.py --seq-parallel 8
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from horovod_tpu.common.compat import shard_map
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import MeshSpec, build_mesh
from horovod_tpu.parallel.ring_attention import attention, ring_attention


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-parallel", type=int, default=0,
                    help="ring size (default: all devices)")
    ap.add_argument("--seq-len", type=int, default=4096,
                    help="TOTAL sequence length across the ring")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--verify", action="store_true",
                    help="cross-check against full attention "
                         "(gathers the whole sequence — small L only)")
    args = ap.parse_args()

    sp = args.seq_parallel or len(jax.devices())
    mesh = build_mesh(MeshSpec(data=1, seq=sp))
    L, H, D = args.seq_len, args.heads, args.head_dim
    assert L % sp == 0, "--seq-len must divide by the ring size"
    print(f"ring attention: {sp} devices x {L // sp} tokens "
          f"= {L} total, {H} heads x {D}")

    rng = np.random.default_rng(0)
    shape = (args.batch, L, H, D)
    q, k, v = (jnp.asarray(rng.standard_normal(shape, dtype=np.float32))
               for _ in range(3))
    seq_sh = NamedSharding(mesh, P(None, "seq"))
    q, k, v = (jax.device_put(t, seq_sh) for t in (q, k, v))

    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq")))

    out = ring(q, k, v)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = ring(q, k, v)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    print(f"ring step: {dt * 1e3:.1f} ms "
          f"({args.batch * L} tokens, causal)")

    if args.verify:
        full = attention(jnp.asarray(jax.device_get(q)),
                         jnp.asarray(jax.device_get(k)),
                         jnp.asarray(jax.device_get(v)))
        err = float(jnp.max(jnp.abs(jnp.asarray(jax.device_get(out))
                                    - full)))
        print(f"max |ring - full| = {err:.2e}")
        assert err < 2e-4, err
        print("ring attention verified against full attention")


if __name__ == "__main__":
    main()
